"""Jitted CRDT kernels over the dense DocState.

Each of the reference's O(n) pointer-walk hot loops (SURVEY.md §3) becomes a
vectorized tensor computation here:

- ``findListElement`` / id lookup (micromerge.ts:731-755)  -> masked argmax
- the concurrent-insert skip scan (micromerge.ts:630-635)  -> masked min over
  a comparison vector (the skip run is contiguous, so its end is the first
  non-skippable position)
- metadata splice (micromerge.ts:638)                      -> masked shift
- ``applyAddRemoveMark``'s 2n-position walk with carried op
  sets (peritext.ts:154-223)                               -> prefix cummax
  carry + bitset algebra over boundary-mask rows
- ``getTextWithFormatting``'s left-inheritance walk
  (peritext.ts:366-390)                                    -> segmented
  carry via cummax over per-element boundary sources

All kernels are pure ``DocState -> DocState`` functions of statically-shaped
arrays: `jit`/`vmap`/`shard_map` compose over them, and `lax.scan` sequences
ops within a causal batch while replicas stay embarrassingly parallel.
"""
from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from peritext_tpu.ops.state import MASK_WORD_BITS, DocState
# Mark-type allowMultiple flags arrive as a runtime input vector
# (schema.allow_multiple_array) so registered mark types take effect
# without stale jit constants.

# Op-row field indices (see encode.py for the host-side encoder).
K_KIND = 0  # 0 pad, 1 insert, 2 delete, 3 mark
K_CTR = 1
K_ACT = 2
K_REF_CTR = 3  # insert: reference elem (0 = HEAD); delete: target elem
K_REF_ACT = 4
K_PAYLOAD = 5  # insert: codepoint
K_MACTION = 6  # 0 addMark, 1 removeMark
K_MTYPE = 7
K_MATTR = 8
K_SKIND = 9  # start boundary: 0 before, 1 after
K_SCTR = 10
K_SACT = 11
K_EKIND = 12  # end boundary: 0 before, 1 after, 2 endOfText
K_ECTR = 13
K_EACT = 14
OP_FIELDS = 15

KIND_PAD = 0
KIND_INSERT = 1
KIND_DELETE = 2
KIND_MARK = 3
# Fast-path only: a fused run of chained inserts (see _apply_text_op).
# Fields: K_CTR = first op counter, K_REF_* = the run's reference element,
# K_PAYLOAD = offset into the side char buffer, K_RUN_LEN = run length.
KIND_INSERT_RUN = 4
K_RUN_LEN = K_MACTION  # field reuse; insert runs carry no mark fields
MAX_RUN_LEN = 64


def _find_elem(state: DocState, ctr, act):
    """Index of the element created by op (ctr@act); (C, found=False) if absent."""
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    match = live & (state.elem_ctr == ctr) & (state.elem_act == act)
    found = jnp.any(match)
    return jnp.argmax(match).astype(jnp.int32), found


def _rga_insert_position(elem_ctr, elem_act, length, op, ranks):
    """RGA insert position (reference micromerge.ts:614-635).

    Position = after the reference element, then past the contiguous run of
    elements whose ids exceed this op's id — the convergence rule for
    concurrent same-position inserts (micromerge.ts:630-635).  The run is
    contiguous by construction, so its end is the first position at or after
    ref+1 that is dead or has a smaller id.  Shared by the faithful per-op
    path and the fast two-phase path so their tie-breaks can never diverge.
    Returns (t, keep, here) masks for masked-shift splicing.
    """
    c = elem_ctr.shape[0]
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < length

    is_head = (op[K_REF_CTR] == 0) & (op[K_REF_ACT] == 0)
    match = live & (elem_ctr == op[K_REF_CTR]) & (elem_act == op[K_REF_ACT])
    idx = jnp.where(is_head, jnp.int32(-1), jnp.argmax(match).astype(jnp.int32))

    op_rank = ranks[op[K_ACT]]
    elem_gt_op = (elem_ctr > op[K_CTR]) | (
        (elem_ctr == op[K_CTR]) & (ranks[elem_act] > op_rank)
    )
    stop = (ar > idx) & ~(live & elem_gt_op)
    t = jnp.min(jnp.where(stop, ar, c)).astype(jnp.int32)
    return t, ar < t, ar == t


def _apply_insert(state: DocState, op, ranks) -> DocState:
    """RGA insert (reference micromerge.ts:614-672)."""
    c = state.capacity
    t, keep, here = _rga_insert_position(
        state.elem_ctr, state.elem_act, state.length, op, ranks
    )

    def splice(arr, value):
        return jnp.where(keep, arr, jnp.where(here, value, jnp.roll(arr, 1)))

    slot_ar = jnp.arange(2 * c, dtype=jnp.int32)
    slot_keep = slot_ar < 2 * t
    slot_new = (slot_ar == 2 * t) | (slot_ar == 2 * t + 1)
    bnd_def = jnp.where(slot_keep, state.bnd_def, jnp.where(slot_new, False, jnp.roll(state.bnd_def, 2)))
    bnd_mask = jnp.where(
        slot_keep[:, None],
        state.bnd_mask,
        jnp.where(slot_new[:, None], jnp.uint32(0), jnp.roll(state.bnd_mask, 2, axis=0)),
    )

    return DocState(
        elem_ctr=splice(state.elem_ctr, op[K_CTR]),
        elem_act=splice(state.elem_act, op[K_ACT]),
        deleted=splice(state.deleted, False),
        chars=splice(state.chars, op[K_PAYLOAD]),
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=state.mark_ctr,
        mark_act=state.mark_act,
        mark_action=state.mark_action,
        mark_type=state.mark_type,
        mark_attr=state.mark_attr,
        length=state.length + 1,
        mark_count=state.mark_count,
    )


def _apply_delete(state: DocState, op, ranks) -> DocState:
    """Tombstone the target element (reference micromerge.ts:677-724).

    Idempotent: re-deleting is a no-op, matching applyListUpdate's
    already-deleted guard (micromerge.ts:689).
    """
    del ranks
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    match = live & (state.elem_ctr == op[K_REF_CTR]) & (state.elem_act == op[K_REF_ACT])
    return dataclasses.replace(state, deleted=state.deleted | match)


def _mark_slot_context(state: DocState, op):
    """Shared boundary-slot context for mark application and patch signals.

    Returns (s_slot, e_slot, slots, defined, carry, src) where carry[p] is
    the nearest pre-op defined set at or left of p (the walk's currentOps,
    peritext.ts:181-186) and src[p] is that set's slot index (-1: none —
    the winner cache gathers through it).  Shared so the patch signals can
    never desynchronize from the state the op actually writes.
    """
    c = state.capacity
    big = jnp.int32(2 * c + 2)
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length

    s_match = live & (state.elem_ctr == op[K_SCTR]) & (state.elem_act == op[K_SACT])
    s_slot = 2 * jnp.argmax(s_match).astype(jnp.int32) + op[K_SKIND]
    e_match = live & (state.elem_ctr == op[K_ECTR]) & (state.elem_act == op[K_EACT])
    e_slot = jnp.where(
        op[K_EKIND] == 2,
        big,
        2 * jnp.argmax(e_match).astype(jnp.int32) + jnp.minimum(op[K_EKIND], 1),
    )

    # Walk-order subtlety: when start and end anchor the same slot the
    # walk's start branch fires first and the end match can never fire
    # afterwards (calculateOpsForPosition checks start before end,
    # peritext.ts:236-241), so the op extends to the end of the document —
    # exactly the endOfText behavior.
    e_slot = jnp.where(e_slot == s_slot, big, e_slot)

    slots = jnp.arange(2 * c, dtype=jnp.int32)
    defined = state.bnd_def & (slots < 2 * state.length)
    src = lax.cummax(jnp.where(defined, slots, jnp.int32(-1)))
    carry = jnp.where(
        (src >= 0)[:, None], state.bnd_mask[jnp.maximum(src, 0)], jnp.uint32(0)
    )
    return s_slot, e_slot, slots, defined, carry, src


def _apply_mark(state: DocState, op, ranks) -> DocState:
    """Write a mark op into the boundary bitsets (reference peritext.ts:154-223).

    Vectorized form of the BEFORE/DURING/AFTER walk.  Derivation (preserving
    a reference subtlety): the walk's carried ``currentOps`` is never updated
    with the op being applied (peritext.ts:181-186), so every write stores
    ``carry_old | op_bit`` for slots in [start, end) and plain ``carry_old``
    at the end slot, where ``carry_old[p]`` is the nearest *pre-op* defined
    set at or left of p.  Written slots: the start slot, every already-defined
    slot strictly inside the range, and the end slot.  If the end slot
    precedes the start slot in walk order, the walk hits AFTER first and only
    the end slot is written (with its carry), the op lands nowhere.
    """
    del ranks
    return _apply_mark_ctx(state, op, _mark_slot_context(state, op))


def _apply_mark_ctx(state: DocState, op, ctx) -> DocState:
    """_apply_mark with a precomputed _mark_slot_context (so a patch-signal
    computation sharing the same instant can reuse one context)."""
    s_slot, e_slot, slots, defined, carry, _ = ctx
    m = state.mark_count
    word = m // MASK_WORD_BITS
    bit = jnp.uint32(1) << (m % MASK_WORD_BITS).astype(jnp.uint32)
    op_bit_row = jnp.zeros_like(state.bnd_mask[0]).at[word].set(bit)

    s_lt_e = s_slot < e_slot
    in_range = (slots >= s_slot) & (slots < e_slot) & s_lt_e
    write = (in_range & ((slots == s_slot) | defined)) | (slots == e_slot)

    new_rows = carry | jnp.where(in_range[:, None], op_bit_row, jnp.uint32(0))
    bnd_mask = jnp.where(write[:, None], new_rows, state.bnd_mask)
    bnd_def = state.bnd_def | write

    return DocState(
        elem_ctr=state.elem_ctr,
        elem_act=state.elem_act,
        deleted=state.deleted,
        chars=state.chars,
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=state.mark_ctr.at[m].set(op[K_CTR]),
        mark_act=state.mark_act.at[m].set(op[K_ACT]),
        mark_action=state.mark_action.at[m].set(op[K_MACTION]),
        mark_type=state.mark_type.at[m].set(op[K_MTYPE]),
        mark_attr=state.mark_attr.at[m].set(op[K_MATTR]),
        length=state.length,
        mark_count=m + 1,
    )


def apply_op(state: DocState, op: jax.Array, ranks: jax.Array) -> DocState:
    """Apply one encoded internal op.  ``op`` is an OP_FIELDS int32 row."""
    kind = jnp.clip(op[K_KIND], 0, 3)
    return lax.switch(
        kind,
        [
            lambda s, o, r: s,  # pad
            _apply_insert,
            _apply_delete,
            _apply_mark,
        ],
        state,
        op,
        ranks,
    )


def apply_ops(state: DocState, ops: jax.Array, ranks: jax.Array) -> DocState:
    """Sequence a causally-ordered op batch with lax.scan.

    Within one replica ops are sequentially dependent (an insert's position
    depends on prior inserts — SURVEY.md §7 "hard parts"); across replicas
    this function vmaps, which is the throughput axis.
    """

    def step(s, op):
        return apply_op(s, op, ranks), None

    final, _ = lax.scan(step, state, ops)
    return final


apply_ops_jit = jax.jit(apply_ops)
apply_ops_vmapped = jax.vmap(apply_ops, in_axes=(0, 0, None))
apply_ops_batch = jax.jit(apply_ops_vmapped)


# ---------------------------------------------------------------------------
# Patch-emitting faithful path (the incremental codepath on device)
# ---------------------------------------------------------------------------


def _walk_signals(ctx, visible, c: int):
    """written/during/visibleIndex planes of the reference mark walk
    (peritext.ts:181-214), from a precomputed slot context.  Shared by the
    interleaved signals and the sorted patch scan so the walk semantics
    (incl. the s_lt_e/endOfText edge) have exactly one definition."""
    s_slot, e_slot, slots, defined, _carry, _src = ctx
    s_lt_e = s_slot < e_slot
    during = (slots >= s_slot) & (slots < e_slot) & s_lt_e
    written = (during & ((slots == s_slot) | defined)) | (slots == e_slot)
    # visibleIndex per slot: before-slot of element i sees the count of
    # visible elements before i; after-slot sees the count through i.
    vcum = jnp.cumsum(visible.astype(jnp.int32))
    vis = jnp.stack([vcum - visible.astype(jnp.int32), vcum], axis=1).reshape(2 * c)
    final_vis = vcum[c - 1] if c > 0 else jnp.int32(0)
    return written, during, vis, final_vis


def _changed_vs_winner(op, op_rank, w_ctr, w_rank, w_action, w_attr, has_winner):
    """The `opsToMarks(current) != opsToMarks(new)` test against the op's
    group winner (reference peritext.ts:294-326 restricted to one group):
    the op must win the LWW tie-break AND flip the effective value.  One
    definition shared by both patch paths."""
    op_wins = ~has_winner | (op[K_CTR] > w_ctr) | (
        (op[K_CTR] == w_ctr) & (op_rank > w_rank)
    )
    old_active = has_winner & (w_action == 0)
    new_active = op[K_MACTION] == 0
    value_differs = (old_active != new_active) | (
        old_active & new_active & (w_attr != op[K_MATTR])
    )
    return op_wins & value_differs


def _mark_patch_signals(state: DocState, op, ranks, multi):
    """Per-slot patch signals for a mark op (reference peritext.ts:181-214).

    Returns (written, during, changed, vis, final_vis):
    - written[p]: the walk writes slot p (start slot, defined slots strictly
      inside the range, end slot)
    - during[p]: the DURING window [start, end)
    - changed[p]: adding this op to slot p's inherited set changes the
      *effective* marks there — the `opsToMarks(current) != opsToMarks(new)`
      test, restricted to the op's own resolution group (its mark type, or
      its (type, comment-id) group for allowMultiple marks), because adding
      one op cannot change any other group's resolution
    - vis[p]: the reference walk's visibleIndex at slot p's patch logic
    - final_vis: total visible length (also objLength for patch clamping)
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length

    ctx = _mark_slot_context(state, op)
    carry = ctx[4]
    written, during, vis, final_vis = _walk_signals(ctx, live & ~state.deleted, c)

    # Inherited (pre-op) sets at every slot, as presence bits.
    present = expand_mask_bits(carry, state.max_mark_ops)  # [2C, M]

    # Winner of the op's own resolution group per slot.
    m_live = jnp.arange(state.max_mark_ops, dtype=jnp.int32) < state.mark_count
    is_multi = multi[op[K_MTYPE]]
    group = m_live & (state.mark_type == op[K_MTYPE]) & (
        ~is_multi | (state.mark_attr == op[K_MATTR])
    )
    cand = present & group[None, :]
    # Two-pass lexicographic argmax on (ctr, rank) without int64:
    rank = ranks[state.mark_act]
    neg = jnp.int32(-(2**31) + 1)
    ctrs = jnp.where(cand, state.mark_ctr[None, :], neg)
    max_ctr = jnp.max(ctrs, axis=1)  # [2C]
    tie = cand & (state.mark_ctr[None, :] == max_ctr[:, None])
    rks = jnp.where(tie, rank[None, :], neg)
    max_rank = jnp.max(rks, axis=1)
    win = tie & (rank[None, :] == max_rank[:, None])  # one-hot winner per slot
    has_winner = jnp.any(cand, axis=1)

    w_action = jnp.sum(jnp.where(win, state.mark_action[None, :], 0), axis=1)
    w_attr = jnp.sum(jnp.where(win, state.mark_attr[None, :], 0), axis=1)
    w_ctr = jnp.where(has_winner, max_ctr, jnp.int32(-1))
    w_rank = jnp.where(has_winner, max_rank, jnp.int32(-1))

    changed = _changed_vs_winner(
        op, ranks[op[K_ACT]], w_ctr, w_rank, w_action, w_attr, has_winner
    )
    return written, during, changed, vis, final_vis


def apply_op_patched(state: DocState, op: jax.Array, ranks: jax.Array, multi: jax.Array):
    """Faithful per-op application + a fixed-shape patch record.

    The record feeds host-side patch assembly (universe.assemble_patches),
    which produces the exact reference Patch stream (micromerge.ts:25-30).
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    visible = live & ~state.deleted
    kind = jnp.clip(op[K_KIND], 0, 3)
    is_insert = kind == KIND_INSERT
    is_delete = kind == KIND_DELETE
    is_mark = kind == KIND_MARK

    # Insert: visible position + inherited marks (pre-insert closest defined
    # boundary strictly left of the insertion gap; getActiveMarksAtIndex,
    # peritext.ts:328-330).
    t, _, _ = _rga_insert_position(state.elem_ctr, state.elem_act, state.length, op, ranks)
    ins_index = jnp.sum(visible & (ar < t)).astype(jnp.int32)
    slots = jnp.arange(2 * c, dtype=jnp.int32)
    defined = state.bnd_def & (slots < 2 * state.length)
    src_left = jnp.max(jnp.where(defined & (slots < 2 * t), slots, jnp.int32(-1)))
    ins_mask = jnp.where(
        src_left >= 0,
        lax.dynamic_slice_in_dim(state.bnd_mask, jnp.maximum(src_left, 0), 1, axis=0)[0],
        jnp.uint32(0),
    )

    # Delete: visible position of the target; valid only if not tombstoned.
    d_match = live & (state.elem_ctr == op[K_REF_CTR]) & (state.elem_act == op[K_REF_ACT])
    d_idx = jnp.argmax(d_match).astype(jnp.int32)
    del_valid = jnp.any(d_match) & ~state.deleted[d_idx]
    del_index = jnp.sum(visible & (ar < d_idx)).astype(jnp.int32)

    written, during, changed, vis, final_vis = _mark_patch_signals(state, op, ranks, multi)

    record = {
        "kind": kind,
        "index": jnp.where(is_insert, ins_index, del_index),
        "valid": is_insert | (is_delete & del_valid) | is_mark,
        "char": op[K_PAYLOAD],
        "obj_len": final_vis,
        "ins_mask": ins_mask,
        "written": written & is_mark,
        "during": during & is_mark,
        "changed": changed & is_mark,
        "vis": vis,
    }
    new_state = apply_op(state, op, ranks)
    return new_state, record


def _first_k_set(mask, k: int):
    """Indices of the first ``k`` set positions of a [N] bool vector, in
    ascending order: one cumsum + ``k`` binary searches (the running count
    is non-decreasing, so the first position where it reaches j+1 IS the
    j-th set position).  Scatter-free AND sort-free — lax.top_k lowers to
    a per-row partial sort that measures ~1.3 s at the bench record shape
    on CPU; this formulation is two orders of magnitude cheaper and
    equally TPU-friendly.  Returns (idx [k] i32 clamped into range,
    ok [k] bool, total i32 — the full set-bit count, for overflow
    guards)."""
    n = mask.shape[0]
    cs = jnp.cumsum(mask.astype(jnp.int32))
    q = jnp.arange(1, k + 1, dtype=jnp.int32)
    idx = jnp.searchsorted(cs, q, side="left")
    ok = q <= cs[n - 1]
    return jnp.minimum(idx, n - 1).astype(jnp.int32), ok, cs[n - 1]


def compact_mark_records(
    written, during, changed, vis, obj_len, cand_def, span_cap: int, cand_cap: int
):
    """Device-side compaction of per-slot mark patch planes into run tables.

    The host's ``_mark_patch_list`` walk consumes the ``[M, 2C]`` planes
    only through their *emitted spans*: a patch opens at every written
    DURING slot whose effective marks change, spans to the next written
    slot's visibleIndex (or objLength), and survives the finishPartialPatch
    filters (peritext.ts:269-281).  All of that is a closed per-slot
    predicate, so the spans compact on device and the D2H readback becomes
    output-proportional: ``[M, span_cap]`` run tables instead of four
    ``[M, 2C]`` planes (ISSUE 8 tentpole; the event stream must be
    proportional to the edits, not the document — eg-walker/Collabs make
    the same argument host-side).

    Cost structure: every written slot is DEFINED in the post-batch
    boundary plane (anchor writes define their slots; in-range writes
    require definedness), and defined slots number at most 2x the mark
    table — the host-census bound behind the static ``cand_cap``.  So the
    2C axis is left once per replica (``_first_k_set`` over ``cand_def``,
    one [2C] cumsum shared by ALL mark rows) and everything per-row runs
    on the tiny compacted [M, cand_cap] candidate axis: gathers, one
    cumsum, binary searches.  No full-width per-row passes at all.

    ``cand_def`` must be in the SAME slot coordinates as the record
    planes — true for the sorted patched merge, whose mark records live
    on final post-placement coordinates.  The interleaved scan's records
    are per-op-INSTANT (each splice shifts the slot axis), so it passes
    ``cand_def=None`` and the compaction runs full-width per row instead
    (two [M, 2C] cumsums) — costlier, but that path is the deep-batch
    fallback whose asymptotics are already one scan step per op.

    Returns ``(run_start [M, K] i32, run_end [M, K] i32, count [M] i32)``:
    lanes hold the row's open (written & during & changed) slots in slot
    order — the walk's emission order — with the finishPartialPatch
    filters applied per lane (a filtered lane reads ``end <= start``; the
    host skips it).  ``count`` is the TRUE open-slot count: the host
    compares it against ``span_cap`` and falls back to the planes readback
    on overflow, so the cap never silently truncates a patch stream.
    """
    m, two_c = written.shape
    if cand_def is None:
        # Instant-coordinate planes: the full slot axis is its own
        # (exact) candidate set.
        d = two_c
        cand_total = None
        w_c = written
        open_c = written & during & changed
        vis_c = vis
    else:
        d = min(cand_cap, two_c)
        cand_idx, cand_ok, cand_total = _first_k_set(cand_def, d)
        gi = jnp.broadcast_to(cand_idx[None, :], (m, d))
        w_c = jnp.take_along_axis(written, gi, axis=1) & cand_ok[None, :]
        open_c = (
            w_c
            & jnp.take_along_axis(during, gi, axis=1)
            & jnp.take_along_axis(changed, gi, axis=1)
        )
        vis_c = jnp.take_along_axis(vis, gi, axis=1)

    # First span_cap open candidates per row, ascending (candidates are
    # already in slot order).
    k = min(span_cap, d)
    cs_open = jnp.cumsum(open_c.astype(jnp.int32), axis=1)
    q = jnp.arange(1, k + 1, dtype=jnp.int32)
    sel = jax.vmap(lambda a: jnp.searchsorted(a, q, side="left"))(cs_open)
    lane_ok = q[None, :] <= cs_open[:, d - 1 :]
    sel_c = jnp.minimum(sel, d - 1)
    start = jnp.take_along_axis(vis_c, sel_c, axis=1)

    # Patch end: the next WRITTEN candidate after the selected slot (the
    # walk's closing boundary), else objLength.
    cs_w = jnp.cumsum(w_c.astype(jnp.int32), axis=1)
    wk = jnp.take_along_axis(cs_w, sel_c, axis=1)
    nxt = jax.vmap(lambda a, t: jnp.searchsorted(a, t, side="left"))(cs_w, wk + 1)
    has_nxt = nxt < d
    end_raw = jnp.where(
        has_nxt,
        jnp.take_along_axis(vis_c, jnp.minimum(nxt, d - 1), axis=1),
        obj_len[:, None],
    )

    # finishPartialPatch filters (peritext.ts:269-281), per lane: a
    # filtered lane stores (0, 0) so the host's end > start test skips it.
    ok = lane_ok & (end_raw > start) & (start < obj_len[:, None])
    run_start = jnp.where(ok, start, 0)
    run_end = jnp.where(ok, jnp.minimum(end_raw, obj_len[:, None]), 0)
    # Self-guard: the host-census bound makes a candidate overflow
    # impossible (defined slots <= 2x mark table), but if it ever broke,
    # spans beyond the candidate axis would silently drop — so report a
    # beyond-cap count instead and let the host's overflow fallback read
    # the planes.
    count = cs_open[:, d - 1]
    if cand_total is not None:
        count = jnp.where(
            cand_total > d, jnp.full((m,), span_cap + 1, jnp.int32), count
        )
    if span_cap > k:  # degenerate tiny-capacity shape: pad the lanes
        pad = ((0, 0), (0, span_cap - k))
        run_start = jnp.pad(run_start, pad)
        run_end = jnp.pad(run_end, pad)
    return run_start, run_end, count


def apply_ops_patched(
    state: DocState,
    ops: jax.Array,
    ranks: jax.Array,
    multi: jax.Array,
    readback: str = "planes",
    span_cap: int = 8,
):
    def step(s, op):
        return apply_op_patched(s, op, ranks, multi)

    new_state, rec = lax.scan(step, state, ops)
    if readback != "compact":
        return new_state, rec
    # Output-proportional readback: the mark planes compact to run tables
    # (cand_def=None — the interleaved records are per-op-INSTANT slot
    # coordinates, see compact_mark_records), and fields the host already
    # holds in the encoded op rows (kind, the insert payload, obj_len —
    # compact mark patches carry their own clamped ends) drop from the
    # record dict entirely.
    run_start, run_end, count = compact_mark_records(
        rec["written"], rec["during"], rec["changed"], rec["vis"],
        rec["obj_len"], None, span_cap, 0,
    )
    return new_state, {
        "index": rec["index"],
        "valid": rec["valid"],
        "ins_mask": rec["ins_mask"],
        "mstart": run_start,
        "mend": run_end,
        "mcount": count,
    }


@functools.lru_cache(maxsize=None)
def _apply_ops_patched_jit(readback: str, span_cap: int):
    return jax.jit(
        functools.partial(apply_ops_patched, readback=readback, span_cap=span_cap)
    )


def apply_ops_patched_jit(
    state, ops, ranks, multi, readback: str = "planes", span_cap: int = 8
):
    if readback == "planes":  # cap unused: keep ONE jit cache entry
        span_cap = 8
    return _apply_ops_patched_jit(readback, span_cap)(state, ops, ranks, multi)


@functools.lru_cache(maxsize=None)
def _apply_ops_patched_batch(readback: str, span_cap: int):
    return jax.jit(
        jax.vmap(
            functools.partial(
                apply_ops_patched, readback=readback, span_cap=span_cap
            ),
            in_axes=(0, 0, None, None),
        )
    )


def apply_ops_patched_batch(
    states, ops, ranks, multi, readback: str = "planes", span_cap: int = 8
):
    if readback == "planes":  # cap unused: keep ONE jit cache entry
        span_cap = 8
    return _apply_ops_patched_batch(readback, span_cap)(states, ops, ranks, multi)


# ---------------------------------------------------------------------------
# Fast merge path: kind-split two-phase application
# ---------------------------------------------------------------------------
#
# State-equivalence argument for reordering a causally-sorted op batch into
# (all inserts+deletes, in order) followed by (all mark ops, in order):
# a mark op writes only boundary sets, whose contents are keyed by stable
# element identity; an insert splices *undefined* boundary slots, so it
# neither reads nor changes any defined set, and a delete only flips a
# tombstone flag that mark application ignores for state purposes (the
# visible index matters only for patch emission, which this path does not
# do).  Hence mark<->text adjacent transpositions preserve the final state,
# and the two-phase order is reachable by such transpositions while keeping
# each kind's internal order.  Patch-faithful application uses the
# interleaved apply_ops path instead.


def _apply_text_op(carry, op, ranks, char_buf=None):
    """Insert/delete on the reduced text state (no boundary tables).

    carry = (elem_ctr, elem_act, deleted, chars, orig_idx, length).
    ``orig_idx`` tags each element with its pre-batch position (-1 for
    elements inserted by this batch) so the boundary tables can be permuted
    once at the end of the phase instead of shifted per insert.

    With ``char_buf`` given, KIND_INSERT_RUN rows apply a whole chained
    insert run (one input op's characters) in a single step.  Chains land
    contiguously in the RGA order: the first op takes the normal position
    (skip run included), and each subsequent op references the one before
    it, whose successor — whatever originally followed the insertion point —
    has a *smaller* id than the chain's first op (that is what ended the
    skip run), hence smaller than every later chain op, so no further
    skipping can occur.  Characters come from ``char_buf`` at
    K_PAYLOAD..K_PAYLOAD+K_RUN_LEN; element counters are K_CTR..K_CTR+len-1.
    """
    elem_ctr, elem_act, deleted, chars, orig_idx, length = carry
    ar = jnp.arange(elem_ctr.shape[0], dtype=jnp.int32)
    live = ar < length
    is_insert = op[K_KIND] == KIND_INSERT
    is_run = (op[K_KIND] == KIND_INSERT_RUN) if char_buf is not None else jnp.bool_(False)
    is_delete = op[K_KIND] == KIND_DELETE

    # Delete: tombstone the match.
    match = live & (elem_ctr == op[K_REF_CTR]) & (elem_act == op[K_REF_ACT])
    deleted_after_del = deleted | (match & is_delete)

    # Insert: shared position rule, then masked-shift splice of k elements
    # (k = 1 for plain inserts).
    k = jnp.where(is_run, op[K_RUN_LEN], jnp.int32(1))
    t, _, _ = _rga_insert_position(elem_ctr, elem_act, length, op, ranks)
    keep = ar < t
    block = (ar >= t) & (ar < t + k)
    offset = ar - t  # position within the inserted block where `block`

    if char_buf is not None:
        run_chars = lax.dynamic_slice_in_dim(
            char_buf, op[K_PAYLOAD] * is_run.astype(jnp.int32), MAX_RUN_LEN
        )
        block_chars = run_chars[jnp.clip(offset, 0, MAX_RUN_LEN - 1)]
        char_vals = jnp.where(is_run, block_chars, op[K_PAYLOAD])
    else:
        char_vals = op[K_PAYLOAD]

    def splice(arr, value):
        return jnp.where(keep, arr, jnp.where(block, value, jnp.roll(arr, k)))

    any_insert = is_insert | is_run
    new_carry = (
        jnp.where(any_insert, splice(elem_ctr, op[K_CTR] + offset), elem_ctr),
        jnp.where(any_insert, splice(elem_act, op[K_ACT]), elem_act),
        jnp.where(any_insert, splice(deleted_after_del, False), deleted_after_del),
        jnp.where(any_insert, splice(chars, char_vals), chars),
        jnp.where(any_insert, splice(orig_idx, jnp.int32(-1)), orig_idx),
        length + jnp.where(any_insert, k, 0),
    )
    return new_carry, None


def _slot_permutation(orig_idx):
    """Flat slot-axis form of a text phase's element permutation:
    ``(valid [2C], flat_src [2C])`` mapping each post-splice boundary slot
    to its pre-splice slot.  THE one definition for every plane that rides
    the splice (boundary tables, winner cache) — and deliberately flat:
    a [C, 2, ...]-view gather costs the compiler full-plane layout copies
    (PROFILE_r05.md)."""
    c = orig_idx.shape[0]
    slots = jnp.arange(2 * c, dtype=jnp.int32)
    valid = (orig_idx >= 0)[slots // 2]
    flat_src = 2 * jnp.maximum(orig_idx, 0)[slots // 2] + slots % 2
    return valid, flat_src


def _permute_boundaries(bnd_def, bnd_mask, orig_idx):
    """Re-align boundary tables after a text phase, in one gather."""
    valid, flat_src = _slot_permutation(orig_idx)
    new_def = jnp.where(valid, bnd_def[flat_src], False)
    new_mask = jnp.where(valid[:, None], bnd_mask[flat_src], jnp.uint32(0))
    return new_def, new_mask


def _apply_mark_fast(carry, op, elem_ctr, elem_act, length):
    """Mark application without patches, cummax, or full-width gathers.

    Only three kinds of slots are written (see _apply_mark's derivation):
    already-defined slots inside [start, end) OR in their own op bit (their
    carry is their own row); the start slot takes (nearest defined row at or
    left of it) | bit; the end slot takes its carry row unchanged.  The two
    carry lookups are single dynamic row reads.
    """
    bnd_def, bnd_mask, mark_ctr, mark_act, mark_action, mark_type, mark_attr, mark_count = carry
    c = elem_ctr.shape[0]
    is_mark = op[K_KIND] == KIND_MARK
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < length
    big = jnp.int32(2 * c + 2)

    s_match = live & (elem_ctr == op[K_SCTR]) & (elem_act == op[K_SACT])
    s_slot = 2 * jnp.argmax(s_match).astype(jnp.int32) + op[K_SKIND]
    e_match = live & (elem_ctr == op[K_ECTR]) & (elem_act == op[K_EACT])
    e_slot = jnp.where(
        op[K_EKIND] == 2,
        big,
        2 * jnp.argmax(e_match).astype(jnp.int32) + jnp.minimum(op[K_EKIND], 1),
    )
    # Same-slot anchors: start branch wins in the walk -> endOfText behavior
    # (see _mark_slot_context).
    e_slot = jnp.where(e_slot == s_slot, big, e_slot)

    slots = jnp.arange(2 * c, dtype=jnp.int32)
    defined = bnd_def & (slots < 2 * length)

    def carry_row_at(p):
        src = jnp.max(jnp.where(defined & (slots <= p), slots, jnp.int32(-1)))
        row = lax.dynamic_slice_in_dim(bnd_mask, jnp.maximum(src, 0), 1, axis=0)[0]
        return jnp.where(src >= 0, row, jnp.uint32(0))

    m = mark_count
    bit = jnp.uint32(1) << (m % MASK_WORD_BITS).astype(jnp.uint32)
    op_bit_row = jnp.zeros_like(bnd_mask[0]).at[m // MASK_WORD_BITS].set(bit)

    s_lt_e = s_slot < e_slot
    in_range = (slots >= s_slot) & (slots < e_slot) & s_lt_e & is_mark

    # Defined slots inside the range OR in the op bit.
    new_mask = jnp.where(
        (in_range & defined)[:, None], bnd_mask | op_bit_row[None, :], bnd_mask
    )
    # Start slot: carry | bit (single row update).
    row_s = (carry_row_at(s_slot) | op_bit_row)[None, :]
    write_s = is_mark & s_lt_e
    new_mask = jnp.where(
        write_s,
        lax.dynamic_update_slice_in_dim(new_mask, row_s, s_slot, axis=0),
        new_mask,
    )
    # End slot: plain carry row (no bit).  Skipped for endOfText.
    e_clamped = jnp.minimum(e_slot, jnp.int32(2 * c - 1))
    write_e = is_mark & (e_slot < 2 * c)
    row_e = carry_row_at(e_clamped)[None, :]
    new_mask = jnp.where(
        write_e,
        lax.dynamic_update_slice_in_dim(new_mask, row_e, e_clamped, axis=0),
        new_mask,
    )
    new_def = bnd_def | (in_range & defined) | ((slots == s_slot) & write_s) | (
        (slots == e_slot) & write_e
    )

    new_carry = (
        new_def,
        new_mask,
        jnp.where(is_mark, mark_ctr.at[m].set(op[K_CTR]), mark_ctr),
        jnp.where(is_mark, mark_act.at[m].set(op[K_ACT]), mark_act),
        jnp.where(is_mark, mark_action.at[m].set(op[K_MACTION]), mark_action),
        jnp.where(is_mark, mark_type.at[m].set(op[K_MTYPE]), mark_type),
        jnp.where(is_mark, mark_attr.at[m].set(op[K_MATTR]), mark_attr),
        m + is_mark.astype(jnp.int32),
    )
    return new_carry, None


def merge_step(
    state: DocState,
    text_ops: jax.Array,
    mark_ops: jax.Array,
    ranks: jax.Array,
    char_buf: jax.Array | None = None,
) -> DocState:
    """Fast batched merge: text phase -> boundary permute -> mark phase.

    The production remote-ingestion path (no patch emission).  ``text_ops``
    holds the batch's inserts/deletes in causal order, ``mark_ops`` its mark
    ops in causal order; both padded with KIND_PAD rows.  With ``char_buf``,
    text rows may be fused KIND_INSERT_RUN rows (encode.fuse_insert_runs),
    applying a whole typing run per scan step.
    """
    c = state.capacity
    orig_idx = jnp.arange(c, dtype=jnp.int32)

    text_carry = (state.elem_ctr, state.elem_act, state.deleted, state.chars, orig_idx, state.length)
    (elem_ctr, elem_act, deleted, chars, orig_idx, length), _ = lax.scan(
        lambda cry, op: _apply_text_op(cry, op, ranks, char_buf), text_carry, text_ops
    )
    bnd_def, bnd_mask = _permute_boundaries(state.bnd_def, state.bnd_mask, orig_idx)

    mark_carry = (
        bnd_def,
        bnd_mask,
        state.mark_ctr,
        state.mark_act,
        state.mark_action,
        state.mark_type,
        state.mark_attr,
        state.mark_count,
    )
    (bnd_def, bnd_mask, mark_ctr, mark_act, mark_action, mark_type, mark_attr, mark_count), _ = lax.scan(
        lambda cry, op: _apply_mark_fast(cry, op, elem_ctr, elem_act, length),
        mark_carry,
        mark_ops,
    )

    return DocState(
        elem_ctr=elem_ctr,
        elem_act=elem_act,
        deleted=deleted,
        chars=chars,
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=mark_ctr,
        mark_act=mark_act,
        mark_action=mark_action,
        mark_type=mark_type,
        mark_attr=mark_attr,
        length=length,
        mark_count=mark_count,
    )


merge_step_vmapped = jax.vmap(merge_step, in_axes=(0, 0, 0, None))
merge_step_batch = jax.jit(merge_step_vmapped)
merge_step_fused_vmapped = jax.vmap(merge_step, in_axes=(0, 0, 0, None, 0))
merge_step_fused_batch = jax.jit(merge_step_fused_vmapped)


# ---------------------------------------------------------------------------
# Sort-based batch integration: place a whole op batch in O(depth) rounds
# ---------------------------------------------------------------------------
#
# The scan paths above keep the reference's asymptotics — L ops cost L
# sequential O(C) steps.  This path integrates an entire causally-ordered
# text-op batch in D vectorized rounds, where D is the batch's reference
# *depth* (how many ops chain through elements created earlier in the same
# batch — computed on host by encode.compute_rounds; 1 for fully concurrent
# batches, small in practice because insert runs are pre-fused).
#
# Correctness (simultaneous placement == sequential application): for ops
# whose references all pre-exist the round, sequential RGA application in any
# causal order equals a stable merge keyed by (t, descending op id), where
# t(op) = min{ j > idx(ref) : ~(alive_j & id_j > id_op) } is the op's skip-run
# stop (micromerge.ts:630-635) computed against the *pre-round* array:
# - same t, any refs: the later-applied op's scan stops exactly at (greater
#   id) or immediately before (smaller id) the earlier op's block, which is
#   the descending-id order.
# - different t: an op B can only encounter a previously placed block A
#   inside its skip run when every pre-round element between them exceeds
#   B's id; A's own stop rule then forces t_A >= t_B unless id_A > id_B, so
#   a smaller-id block can never land strictly inside B's run — B's stop
#   element (hence its placement) is unchanged by A, and positions shift by
#   exactly the blocks placed at or before them.
# Ops whose reference is created by the batch itself go in a later round
# (their reference then pre-exists), and rounds respect causal order, so the
# round decomposition is a causal-order-preserving reordering — which
# preserves the final state exactly as the two-phase argument above.
# Deletes never affect placement (the stop rule reads allocation, not
# tombstones) and apply as one [L, C] masked match per round.


# Splice strategy for sort-based placement: "sort" (default) materializes
# each round's output with a stable sort by destination — scatter-free, since
# XLA lowers generic scatters near-serially (measured 8.9x whole-bench on
# CPU; scatters are the known slow path on TPU too).  "scatter" keeps the
# .at[].set splice for A/B.  Read at import/trace time: set PERITEXT_SPLICE
# before importing (bench A/B runs set it per subprocess).
_SPLICE_MODE = os.environ.get("PERITEXT_SPLICE", "sort")
if _SPLICE_MODE not in ("sort", "scatter", "roll"):
    raise ValueError(
        f"PERITEXT_SPLICE={_SPLICE_MODE!r}: must be 'sort', 'scatter' or 'roll'"
    )


def _place_round(carry, r, ops, round_of, ranks, char_buf, maxk: int):
    """Apply every round-r text op simultaneously (one scatter pass)."""
    elem_ctr, elem_act, deleted, chars, orig_idx, length = carry
    c = elem_ctr.shape[0]
    ar = jnp.arange(c, dtype=jnp.int32)
    alive = ar < length

    kind = ops[:, K_KIND]
    active = round_of == r
    is_ins = active & ((kind == KIND_INSERT) | (kind == KIND_INSERT_RUN))
    is_run = kind == KIND_INSERT_RUN
    is_del = active & (kind == KIND_DELETE)

    ref_ctr = ops[:, K_REF_CTR]
    ref_act = ops[:, K_REF_ACT]
    ref_match = (
        alive[None, :]
        & (elem_ctr[None, :] == ref_ctr[:, None])
        & (elem_act[None, :] == ref_act[:, None])
    )  # [L, C]

    # Deletes: tombstone every match in one pass.
    deleted = deleted | (ref_match & is_del[:, None]).any(axis=0)

    # Insert placement: the shared skip-run stop rule, batched over ops.
    ctr_i = ops[:, K_CTR]
    rank_i = ranks[ops[:, K_ACT]]
    is_head = (ref_ctr == 0) & (ref_act == 0)
    idx = jnp.where(is_head, jnp.int32(-1), jnp.argmax(ref_match, axis=1).astype(jnp.int32))
    elem_rank = ranks[elem_act]
    gt = (elem_ctr[None, :] > ctr_i[:, None]) | (
        (elem_ctr[None, :] == ctr_i[:, None]) & (elem_rank[None, :] > rank_i[:, None])
    )  # [L, C]
    stop = (ar[None, :] > idx[:, None]) & ~(alive[None, :] & gt)
    t = jnp.min(jnp.where(stop, ar[None, :], c), axis=1).astype(jnp.int32)  # [L]

    k = jnp.where(is_run, ops[:, K_RUN_LEN], 1) * is_ins.astype(jnp.int32)  # [L]

    # Final block starts: stable order (t, descending op id) among the
    # round's inserts; inactive ops contribute k = 0.
    id_gt = (ctr_i[None, :] > ctr_i[:, None]) | (
        (ctr_i[None, :] == ctr_i[:, None]) & (rank_i[None, :] > rank_i[:, None])
    )  # [L, L]: op j's id > op i's id
    before = (t[None, :] < t[:, None]) | ((t[None, :] == t[:, None]) & id_gt)
    s = t + jnp.sum(k[None, :] * before.astype(jnp.int32), axis=1)  # [L]

    # Existing elements shift right by every block placed at or before them.
    shifts = jnp.sum(k[:, None] * (t[:, None] <= ar[None, :]).astype(jnp.int32), axis=0)
    dest_exist = jnp.where(alive, ar + shifts, c)  # dead slots drop

    # Op-block values and destinations, [L, maxk].
    off = jnp.arange(maxk, dtype=jnp.int32)
    in_block = (off[None, :] < k[:, None]) & is_ins[:, None]
    dest_ops = jnp.where(in_block, s[:, None] + off[None, :], c)
    buf_idx = jnp.clip(ops[:, K_PAYLOAD, None] + off[None, :], 0, char_buf.shape[0] - 1)
    block_chars = jnp.where(
        is_run[:, None], char_buf[buf_idx], ops[:, K_PAYLOAD, None]
    )
    block_ctr = ctr_i[:, None] + off[None, :]
    block_act = jnp.broadcast_to(ops[:, K_ACT, None], (ops.shape[0], maxk))

    zero_blk = jnp.zeros_like(block_ctr)
    new_length = length + jnp.sum(k)
    if _SPLICE_MODE == "roll":
        # Roll splice: move existing elements right by their displacement
        # with MSB-first binary-decomposed rolls, then overwrite block
        # positions from small one-hot reductions.  ceil(log2(L*maxk+1))
        # roll+select passes over [C] planes — cheaper than a bitonic sort
        # over C+L*maxk lanes, and scatter-free.
        #
        # Correctness of the greedy bit decomposition: displacements are
        # non-decreasing along positions (shifts is a cumulative count), and
        # MSB-first keeps every alive remainder below the current step's
        # doubled width; if a mover (rem >= step) could land on an alive
        # slower value (0 < rem < step), the two remainders mod 2*step
        # would have to differ by more than the values' destination gap
        # allows — impossible for monotone displacements (proof by the
        # mod-2^(b+1) window: rem_X >= 2^b, delta <= 2^b - 2 forces
        # rem_Y = rem_X + delta mod 2^(b+1) >= 2^b, a contradiction).
        # Stale source copies are marked DEAD (-1) so they never move again;
        # every position below the new length is re-covered by a mover, a
        # block lane, or its unmoved occupant, and the tail is masked to
        # the scatter fills.
        rem = shifts  # [C]; beyond-length lanes inherit the running count
        planes = [
            elem_ctr,
            elem_act,
            deleted.astype(jnp.int32),
            chars,
            orig_idx,
        ]
        max_disp = ops.shape[0] * maxk
        for b in reversed(range(max_disp.bit_length())):
            step = 1 << b
            moved_rem = jnp.roll(rem, step)
            sel = (moved_rem >= step) & (ar >= step)  # block wraparound
            planes = [
                jnp.where(sel, jnp.roll(p, step), p) for p in planes
            ]
            rem = jnp.where(sel, moved_rem - step, jnp.where(rem >= step, -1, rem))

        # Block fill: each output position belongs to at most one op block
        # (destinations are unique), so masked int max-reductions are exact.
        in_blk = (
            is_ins[:, None]
            & (ar[None, :] >= s[:, None])
            & (ar[None, :] < (s + k)[:, None])
        )  # [L, C]
        neg = jnp.int32(-(2**31) + 1)
        blk_any = in_blk.any(axis=0)

        def from_ops(vals_l):  # [L] -> [C] masked max over the owning op
            return jnp.max(jnp.where(in_blk, vals_l[:, None], neg), axis=0)

        blk_ctr = from_ops(ctr_i - s) + ar  # ctr_l + (p - s_l)
        blk_act = from_ops(ops[:, K_ACT])
        # Run chars via the [L*maxk] block-lane one-hot (payload chars for
        # plain inserts ride the same table).
        lane_hit = (
            in_block.reshape(-1)[:, None]
            & (dest_ops.reshape(-1)[:, None] == ar[None, :])
        )  # [L*maxk, C]
        blk_char = jnp.max(
            jnp.where(lane_hit, block_chars.reshape(-1)[:, None], neg), axis=0
        )

        live_out = ar < new_length
        outs = [
            jnp.where(blk_any, blk_ctr, planes[0]),
            jnp.where(blk_any, blk_act, planes[1]),
            jnp.where(blk_any, 0, planes[2]),
            jnp.where(blk_any, blk_char, planes[3]),
            jnp.where(blk_any, -1, planes[4]),
        ]
        fills = (0, 0, 0, 0, -1)
        outs = [jnp.where(live_out, o, f) for o, f in zip(outs, fills)]
        return (
            outs[0],
            outs[1],
            outs[2].astype(bool),
            outs[3],
            outs[4],
            new_length,
        )
    if _SPLICE_MODE == "sort":
        # Scatter-free splice: XLA:TPU lowers generic scatters to a
        # near-serial loop over indices, which dominates the whole merge on
        # hardware.  Destinations are unique, so materializing the output is
        # a stable sort by destination — but only the (dest, lane-id) pair
        # rides the bitonic network; the five payload planes are GATHERED
        # once by the resulting permutation instead of being dragged
        # through every compare-exchange stage (argsort+gather: ~2 planes x
        # log^2(n) passes + 5 one-pass gathers, vs 6 planes x log^2(n)).
        # State-identical to the scatter splice (same suites cover both;
        # PERITEXT_SPLICE selects).
        keys = jnp.concatenate([dest_exist, dest_ops.reshape(-1)])
        take = jnp.argsort(keys, stable=True)[:c]
        planes = [
            (jnp.concatenate([elem_ctr, block_ctr.reshape(-1)]), 0),
            (jnp.concatenate([elem_act, block_act.reshape(-1)]), 0),
            (
                jnp.concatenate([deleted.astype(jnp.int32), zero_blk.reshape(-1)]),
                0,
            ),
            (jnp.concatenate([chars, block_chars.reshape(-1)]), 0),
            (jnp.concatenate([orig_idx, zero_blk.reshape(-1) - 1]), -1),
        ]
        live_out = ar < new_length
        outs = [
            jnp.where(live_out, plane[take], fill) for plane, fill in planes
        ]
        new_carry = (
            outs[0],
            outs[1],
            outs[2].astype(bool),
            outs[3],
            outs[4],
            new_length,
        )
        return new_carry

    def scat(exist_vals, op_vals, fill):
        out = jnp.full(c, fill, exist_vals.dtype)
        out = out.at[dest_exist].set(exist_vals, mode="drop")
        return out.at[dest_ops].set(op_vals, mode="drop")

    new_carry = (
        scat(elem_ctr, block_ctr, 0),
        scat(elem_act, block_act, 0),
        scat(deleted.astype(jnp.int32), zero_blk, 0).astype(bool),
        scat(chars, block_chars, 0),
        scat(orig_idx, zero_blk - 1, -1),
        new_length,
    )
    return new_carry


def place_text_batch(
    elem_ctr, elem_act, deleted, chars, length, text_ops, round_of, num_rounds,
    ranks, char_buf, maxk: int,
):
    """Integrate a causally-ordered text-op batch in ``num_rounds`` rounds.

    Returns the updated element arrays plus the orig-index permutation plane
    (for boundary realignment, as in the two-phase path).  ``num_rounds`` is
    a traced scalar — one compiled program serves any batch depth.
    """
    c = elem_ctr.shape[0]
    carry = (elem_ctr, elem_act, deleted, chars, jnp.arange(c, dtype=jnp.int32), length)
    carry = lax.fori_loop(
        0,
        num_rounds,
        lambda r, cry: _place_round(cry, r, text_ops, round_of, ranks, char_buf, maxk),
        carry,
    )
    return carry


# Batched mark application.  Sequential dependence between mark ops comes
# only from two channels: (1) an op's start/end writes *define* slots that
# later ops' carry lookups can select, and (2) an op's written row becomes
# the base that later in-range ops OR their bit into.  Both channels have a
# closed form over the whole batch:
#
#   final_row(p) = base(p) | OR{ bit_j : j > last_rebase(p), s_j < p < e_j }
#
# where last_rebase(p) is the last op writing p via its start/end slot, and
# base(p) is the row that op wrote — its carry source's row *frozen at that
# time*, which expands recursively through (slot, time) parent links.  The
# recursion is resolved with pointer doubling over the 2M write-nodes
# (S-node = the row op m writes at its start slot, E-node = at its end
# slot): each node's accumulated value ORs its own contribution (bit +
# in-range bits between its parent's time and its own) with its parent
# chain's.  log2(2M) gather rounds replace the M sequential scan steps.


def _batched_anchor_slots(mark_ops, elem_ctr, elem_act, length):
    """Anchor-slot resolution for a whole mark batch at once.

    Same rules as _mark_slot_context / _apply_mark_fast — including the
    same-slot -> endOfText walk-order subtlety (peritext.ts:236-241) —
    batched over the op axis.  Anchors resolve against the *final* element
    plane and are time-independent, so every batch consumer (the batched
    mark phase, the first-definition timeline, and the compact-delta patch
    scan) shares this one definition.  Returns ``(valid, s_slot, e_slot)``
    with e_slot already remapped to the beyond-any-slot sentinel for
    endOfText and same-slot anchors.
    """
    c = elem_ctr.shape[0]
    big = jnp.int32(2 * c + 2)
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < length

    valid = mark_ops[:, K_KIND] == KIND_MARK
    s_match = (
        live[None, :]
        & (elem_ctr[None, :] == mark_ops[:, K_SCTR, None])
        & (elem_act[None, :] == mark_ops[:, K_SACT, None])
    )
    s_slot = 2 * jnp.argmax(s_match, axis=1).astype(jnp.int32) + mark_ops[:, K_SKIND]
    e_match = (
        live[None, :]
        & (elem_ctr[None, :] == mark_ops[:, K_ECTR, None])
        & (elem_act[None, :] == mark_ops[:, K_EACT, None])
    )
    e_slot = jnp.where(
        mark_ops[:, K_EKIND] == 2,
        big,
        2 * jnp.argmax(e_match, axis=1).astype(jnp.int32)
        + jnp.minimum(mark_ops[:, K_EKIND], 1),
    )
    e_slot = jnp.where(e_slot == s_slot, big, e_slot)  # same-slot -> endOfText
    return valid, s_slot, e_slot


def _or_accumulate(mask: jax.Array, bit_rows: jax.Array) -> jax.Array:
    """OR of the selected one-hot bit rows: [N, M] bool x [M, W] uint32.

    Every row of ``bit_rows`` carries a *distinct* bit, so a sum has no
    carries and equals the OR — but a float32 matmul can only be trusted up
    to the 24-bit mantissa.  Split each word into 16-bit halves first: every
    column then sums distinct powers of two below 2^16, exact in float32,
    and the accumulation runs as one MXU-shaped [N, M] x [M, 2W] matmul
    instead of materializing an [N, M, W] integer intermediate.
    """
    sel = mask.astype(jnp.float32)
    lo = (bit_rows & jnp.uint32(0xFFFF)).astype(jnp.float32)
    hi = (bit_rows >> 16).astype(jnp.float32)
    out = sel @ jnp.concatenate([lo, hi], axis=1)  # [N, 2W], exact
    w = bit_rows.shape[1]
    return out[:, :w].astype(jnp.uint32) | (out[:, w:].astype(jnp.uint32) << 16)


def _apply_marks_batch(
    bnd_def, bnd_mask, mark_ops, elem_ctr, elem_act, length, mark_count, w_words,
    perm=None,
):
    """Apply a causally-ordered mark-op batch to the boundary tables at once.

    The batch closed form of the reference's applyAddRemoveMark walk
    (peritext.ts:154-223) under the write-class derivation documented on
    _apply_mark: same anchor rules (including the same-slot -> endOfText
    walk-order subtlety, peritext.ts:236-241) and the same carried
    ``currentOps`` semantics, resolved for all ops simultaneously.
    Bit-exact with scanning _apply_mark_fast over the same rows (differential
    coverage in tests/test_sorted_merge.py).  Returns (bnd_def, bnd_mask).

    ``perm`` (the text phase's orig-index plane) composes the post-splice
    boundary permutation INTO this phase's reads instead of materializing a
    permuted [2C, W] plane first (_permute_boundaries): every access to the
    old tables goes through ``old_rows``/``def_p``, so the full-width plane
    is read once and written once for the whole phase.
    """
    m_ops = mark_ops.shape[0]
    c = elem_ctr.shape[0]
    two_c = 2 * c
    midx = jnp.arange(m_ops, dtype=jnp.int32)
    slots = jnp.arange(two_c, dtype=jnp.int32)

    if perm is not None:
        # Flat slot-axis composition (post-splice slot -> pre-splice slot):
        # one single-axis gather per use (_slot_permutation).
        pvalid, pflat = _slot_permutation(perm)
        def_p = jnp.where(pvalid, bnd_def[pflat], False)

        def old_rows(slot_idx):  # [N] post-splice slots -> [N, W] old rows
            return jnp.where(
                pvalid[slot_idx][:, None],
                bnd_mask[pflat[slot_idx]],
                jnp.uint32(0),
            )

    else:
        def_p = bnd_def

        def old_rows(slot_idx):
            return bnd_mask[slot_idx]

    # Anchor resolution (same rules as _apply_mark_fast, batched).
    valid, s_slot, e_slot = _batched_anchor_slots(mark_ops, elem_ctr, elem_act, length)

    # Bit rows: op m's table index is mark_count + (rank among valid rows).
    # The batch's new bits all land in a narrow WORD WINDOW of the [.., W]
    # plane — at most ceil(M/32)+1 words starting at mark_count//32 — so
    # every batch-bit tensor (B, segment ORs, the accumulation matmuls, the
    # tail plane) is built at window width w_act instead of the full table
    # width W.  At the bench shape (W=32, ~22 mark rows -> w_act=2) this
    # removes the dominant HBM traffic of the whole merge: the [2C, 2W] f32
    # accumulate plane and several full-width [2C, W] intermediates
    # (roofline_r05: see PROFILE notes).  Only the carry ROOT rows (pre-
    # batch rows, bits anywhere) stay full-width, and they ride the tiny
    # [2M, W] node table.
    mpos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    bit_idx = mark_count + mpos  # [M]
    w_act = min((m_ops + MASK_WORD_BITS - 1) // MASK_WORD_BITS + 1, w_words)
    w0 = jnp.clip(mark_count // MASK_WORD_BITS, 0, w_words - w_act)
    bit_off = bit_idx - w0 * MASK_WORD_BITS  # window-relative, in [0, 32*w_act)
    word_ar = jnp.arange(w_act, dtype=jnp.int32)
    B = jnp.where(
        valid[:, None] & (word_ar[None, :] == bit_off[:, None] // MASK_WORD_BITS),
        jnp.uint32(1) << (bit_off[:, None] % MASK_WORD_BITS).astype(jnp.uint32),
        jnp.uint32(0),
    )  # [M, w_act]

    d0 = def_p & (slots < 2 * length)  # defined before the batch

    writes_s = valid & (s_slot < e_slot)
    writes_e = valid & (e_slot < two_c)
    WS = writes_s[:, None] & (slots[None, :] == s_slot[:, None])  # [M, 2C]
    WE = writes_e[:, None] & (slots[None, :] == e_slot[:, None])
    w_any = WS | WE
    written_any = w_any.any(axis=0)  # [2C]
    w_last = jnp.max(jnp.where(w_any, midx[:, None], -1), axis=0)  # [2C]
    f_first = jnp.min(jnp.where(w_any, midx[:, None], m_ops), axis=0)
    # First time each slot is defined: -1 = pre-batch, m_ops+1 = never.
    def_time = jnp.where(
        d0, jnp.int32(-1), jnp.where(written_any, f_first, jnp.int32(m_ops + 1))
    )

    in_range = (
        writes_s[:, None]
        & (slots[None, :] > s_slot[:, None])
        & (slots[None, :] < e_slot[:, None])
    )  # [M, 2C]
    in_range_t = in_range.T  # [2C, M]
    w_any_t = w_any.T

    def carry_node(p):  # p [M] target slots -> (q, prev, seg bits, root row)
        # Nearest slot defined before this op's turn.
        cand = (slots[None, :] <= p[:, None]) & (def_time[None, :] < midx[:, None])
        q = jnp.max(jnp.where(cand, slots[None, :], -1), axis=1)  # [M]
        qc = jnp.maximum(q, 0)
        # Last batch op writing q before this one (-1: q's row is pre-batch).
        wq = w_any_t[qc] & (q >= 0)[:, None]  # [M, M]
        prev_cand = wq & (midx[None, :] < midx[:, None])
        prev = jnp.max(jnp.where(prev_cand, midx[None, :], -1), axis=1)  # [M]
        # Bits ORed into q between prev and this op (in-range, defined).
        seg = in_range_t[qc] & (q >= 0)[:, None]
        seg = seg & (midx[None, :] > prev[:, None]) & (midx[None, :] < midx[:, None])
        seg_bits = _or_accumulate(seg, B)  # [M, w_act] window bits
        # Root base: q's pre-batch row when no batch op rebased it first
        # (full-width — pre-batch bits live anywhere in the table).
        root_row = jnp.where(
            ((prev < 0) & (q >= 0))[:, None] & d0[qc][:, None],
            old_rows(qc),
            jnp.uint32(0),
        )
        return q, prev, seg_bits, root_row

    q_s, prev_s, seg_s, root_s = carry_node(s_slot)
    e_clamped = jnp.minimum(e_slot, jnp.int32(two_c - 1))
    q_e, prev_e, seg_e, root_e = carry_node(e_clamped)

    # Node table: node m = op m's S-write row, node M+m = its E-write row.
    def parent_node(prev, q):
        # prev's S node if its start slot is q, else its E node.
        is_s = s_slot[jnp.maximum(prev, 0)] == q
        return jnp.where(prev < 0, -1, jnp.where(is_s, prev, prev + m_ops))

    # Split accumulation: window bits [2M, w_act] and full-width root rows
    # [2M, W] (each chain has exactly one root node carrying a nonzero
    # root_row; the OR-propagation delivers it to every chain member).
    acc_win = jnp.concatenate([seg_s | B, seg_e], axis=0)
    acc_root = jnp.concatenate([root_s, root_e], axis=0)
    ptr = jnp.concatenate([parent_node(prev_s, q_s), parent_node(prev_e, q_e)])

    # Pointer doubling: fold each node's ancestor chain into its value.
    n_nodes = 2 * m_ops
    steps = max(1, (n_nodes - 1).bit_length())
    for _ in range(steps):
        pc = jnp.maximum(ptr, 0)
        chained = (ptr >= 0)[:, None]
        acc_win = acc_win | jnp.where(chained, acc_win[pc], jnp.uint32(0))
        acc_root = acc_root | jnp.where(chained, acc_root[pc], jnp.uint32(0))
        ptr = jnp.where(ptr >= 0, ptr[pc], ptr)

    # Per-slot final rows.  Full-width pass: written slots are REBASED to
    # their writer's root row (replacing the old row in every word);
    # everything else keeps its old row.  Window pass: the batch's new bits
    # (rebase chain + tail) OR into the w_act active words only.
    wl = jnp.maximum(w_last, 0)
    node_at = jnp.where(s_slot[wl] == slots, wl, wl + m_ops)
    written_col = written_any[:, None]
    # Expand the tiny [2M, W] root table to written slots as a static OR-
    # select chain instead of a [2C]-index gather: the chain stays inside
    # the one full-plane output fusion (a gather materializes its own
    # [2C, W] plane), and the merge is bandwidth-bound ~300:1, so the
    # extra 2M broadcast selects are free VPU work.  Guarded: HLO size and
    # trace time scale with the (padded) node count, so unusually deep
    # mark batches fall back to the gather.
    if n_nodes <= 128:
        root_at = jnp.uint32(0)
        for n in range(n_nodes):
            root_at = root_at | jnp.where(
                (node_at == n)[:, None], acc_root[n], jnp.uint32(0)
            )
    else:
        root_at = acc_root[node_at]
    base_full = jnp.where(written_col, root_at, old_rows(slots))
    start_time = jnp.where(written_any, w_last, -1)
    tail_mask = in_range_t & (midx[None, :] > start_time[:, None])  # [2C, M]
    tail_w = _or_accumulate(tail_mask, B)  # [2C, w_act]
    # Tail bits apply to written rows and to pre-defined rows only (the
    # walk never marks undefined slots) — the old full-width `touched`
    # gate, expressed per window word.  The window delta is scattered back
    # over the word axis with a broadcast compare + tiny-axis gather (both
    # fuse into the single full-plane output pass; a dynamic_update_slice
    # here costs full-plane layout copies instead).
    delta = (
        jnp.where(written_col, acc_win[node_at], jnp.uint32(0))
        | jnp.where(written_col | d0[:, None], tail_w, jnp.uint32(0))
    )  # [2C, w_act]
    # Scatter the window back over the word axis as w_act static broadcast-
    # selects (w_act is ~2) — pure elementwise, fuses into the single full-
    # plane output pass; a word-axis gather here lowers to an extra
    # W-major plane materialization.
    word_full = jnp.arange(w_words, dtype=jnp.int32)
    expanded = jnp.uint32(0)
    for j in range(w_act):
        expanded = expanded | jnp.where(
            word_full[None, :] == w0 + j, delta[:, j][:, None], jnp.uint32(0)
        )
    new_mask = base_full | expanded
    new_def = def_p | written_any
    return new_def, new_mask


def _append_mark_table(state_fields, mark_ops, mark_count, m_cap):
    """Scatter-append a mark batch's rows into the per-replica mark table."""
    mark_ctr, mark_act, mark_action, mark_type, mark_attr = state_fields
    valid = mark_ops[:, K_KIND] == KIND_MARK
    idx = mark_count + jnp.cumsum(valid.astype(jnp.int32)) - 1
    safe = jnp.where(valid, idx, m_cap)

    def scat(col, field):
        return col.at[safe].set(mark_ops[:, field], mode="drop")

    return (
        scat(mark_ctr, K_CTR),
        scat(mark_act, K_ACT),
        scat(mark_action, K_MACTION),
        scat(mark_type, K_MTYPE),
        scat(mark_attr, K_MATTR),
        mark_count + valid.sum().astype(jnp.int32),
    )


def _sorted_tail(
    state: DocState, elem_ctr, elem_act, deleted, chars, orig_idx, length, mark_ops
) -> DocState:
    """Post-placement tail shared by the sorted merges: batched mark phase
    (with the boundary permute composed into its reads) + table append, per
    replica."""
    bnd_def, bnd_mask = _apply_marks_batch(
        state.bnd_def,
        state.bnd_mask,
        mark_ops,
        elem_ctr,
        elem_act,
        length,
        state.mark_count,
        state.bnd_mask.shape[-1],
        perm=orig_idx,
    )
    mark_ctr, mark_act, mark_action, mark_type, mark_attr, mark_count = _append_mark_table(
        (state.mark_ctr, state.mark_act, state.mark_action, state.mark_type, state.mark_attr),
        mark_ops,
        state.mark_count,
        state.max_mark_ops,
    )
    return DocState(
        elem_ctr=elem_ctr,
        elem_act=elem_act,
        deleted=deleted,
        chars=chars,
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=mark_ctr,
        mark_act=mark_act,
        mark_action=mark_action,
        mark_type=mark_type,
        mark_attr=mark_attr,
        length=length,
        mark_count=mark_count,
    )


def merge_step_sorted(
    state: DocState,
    text_ops: jax.Array,
    round_of: jax.Array,
    num_rounds: jax.Array,
    mark_ops: jax.Array,
    ranks: jax.Array,
    char_buf: jax.Array,
    maxk: int,
) -> DocState:
    """Batched merge, both phases vectorized over the whole op batch.

    State-equivalent to merge_step (same two-phase argument); the text phase
    costs O(depth) vectorized rounds instead of O(#text ops) scan steps, and
    the mark phase costs O(log #marks) gather rounds instead of one scan
    step per mark op.
    """
    elem_ctr, elem_act, deleted, chars, orig_idx, length = place_text_batch(
        state.elem_ctr,
        state.elem_act,
        state.deleted,
        state.chars,
        state.length,
        text_ops,
        round_of,
        num_rounds,
        ranks,
        char_buf,
        maxk,
    )
    return _sorted_tail(
        state, elem_ctr, elem_act, deleted, chars, orig_idx, length, mark_ops
    )


@functools.lru_cache(maxsize=None)
def _merge_step_sorted_batch(maxk: int):
    return jax.jit(
        jax.vmap(
            functools.partial(merge_step_sorted, maxk=maxk),
            in_axes=(0, 0, 0, None, 0, None, 0),
        )
    )


def merge_step_sorted_batch(
    states,
    text_ops,
    round_of,
    num_rounds,
    mark_ops,
    ranks,
    char_buf,
    maxk: int,
    chunk: int | None = None,
):
    """Jitted batched entry point; one cache entry per maxk bucket.

    ``chunk`` (or PERITEXT_SORTED_CHUNK) is an opt-in memory valve: the
    placement/mark phases hold O(L*C + M*2C) transients *per replica*, so a
    very large unsharded batch can exceed HBM; chunking launches the same
    program over R-slices sequentially (at most two program shapes: the
    even chunks and one remainder).  Off by default — mesh-sharded batches
    already divide the transients across chips.
    """
    import os

    r = text_ops.shape[0]
    if chunk is None:
        chunk = int(os.environ.get("PERITEXT_SORTED_CHUNK", "0"))
    fn = _merge_step_sorted_batch(maxk)
    nr = jnp.int32(num_rounds)
    if not chunk or chunk >= r:
        return fn(states, text_ops, round_of, nr, mark_ops, ranks, char_buf)
    outs = []
    for i in range(0, r, chunk):
        sl = slice(i, min(i + chunk, r))
        outs.append(
            fn(
                jax.tree.map(lambda x: x[sl], states),
                text_ops[sl],
                round_of[sl],
                nr,
                mark_ops[sl],
                ranks,
                char_buf[sl],
            )
        )
    return jax.tree.map(lambda *xs: jnp.concatenate(xs), *outs)


# ---------------------------------------------------------------------------
# Patch-emitting sorted merge: analytic text records + mark-only scan
# ---------------------------------------------------------------------------
#
# The faithful patch stream is a deterministic function of (pre-batch state,
# delivery-ordered op list).  The sorted placement gives every element's
# FINAL position up front; each op's patch context is then reconstructed
# from a timeline in final coordinates: born[p] / died[p] are the
# batch-stream instants the element at final position p appeared / was
# first tombstoned (+/-_TIME_BIG for pre-batch facts), so "visible at op
# i's instant" is the closed predicate born < t_i < died.  Insert/delete
# patch records (visible index, validity, inherited-mark source slot)
# become vectorized counting over that predicate — no per-op scan.  Mark
# ops still scan (their patch signals read evolving boundary sets), but
# only over the batch's MARK rows, on final coordinates with per-step time
# masks; a text-dominated batch (typing) no longer pays one sequential
# step per character.  Delivery-order fidelity needs run fusion gated on
# stream adjacency (encode.fuse_insert_runs with ``pos``): a fused run is
# modeled as k consecutive instants, which is exactly true only when no
# other op interleaves the chars in the delivery stream.

_TIME_BIG = 1 << 30


def _sorted_text_records(
    elem_ctr, elem_act, orig_idx, length, pre_deleted0,
    text_ops, text_time, mark_time, mark_valid,
):
    """Per-text-row patch records from the final placement + timeline.

    Returns (born, died, q, index0, tvalid, tm) where born/died are the
    [C] timeline arrays, q is each row's target's final position, index0
    the reference walk's visibleIndex at the row's instant
    (micromerge.ts:659 for inserts / 677-699 for deletes), tvalid the
    delete-idempotence validity, and tm the count of mark ops applied
    before the row's instant (its boundary-plane version).
    """
    c = elem_ctr.shape[0]
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < length
    pre = orig_idx >= 0
    pre_del = pre & pre_deleted0[jnp.maximum(orig_idx, 0)]

    kind = text_ops[:, K_KIND]
    is_ins = (kind == KIND_INSERT) | (kind == KIND_INSERT_RUN)
    is_del = kind == KIND_DELETE
    is_run = kind == KIND_INSERT_RUN
    ctr_l = text_ops[:, K_CTR]
    act_l = text_ops[:, K_ACT]
    k = jnp.where(is_run, text_ops[:, K_RUN_LEN], 1) * is_ins.astype(jnp.int32)
    t = text_time

    # born[p]: each batch-born element matches exactly one insert row; char
    # j of a run appeared at instant t + j (delivery-adjacent by fusion
    # gating).  Pre-batch elements: -BIG.
    created = (
        is_ins[:, None]
        & (elem_act[None, :] == act_l[:, None])
        & (elem_ctr[None, :] >= ctr_l[:, None])
        & (elem_ctr[None, :] < (ctr_l + k)[:, None])
    )  # [L, C]
    born_batch = jnp.sum(
        jnp.where(created, t[:, None] + (elem_ctr[None, :] - ctr_l[:, None]), 0),
        axis=0,
    )
    in_batch = created.any(axis=0)
    born = jnp.where(
        pre | ~in_batch, jnp.int32(-_TIME_BIG), born_batch.astype(jnp.int32)
    )

    # died[p]: first tombstoning instant (idempotent deletes: min).
    del_match = (
        is_del[:, None]
        & (elem_ctr[None, :] == text_ops[:, K_REF_CTR, None])
        & (elem_act[None, :] == text_ops[:, K_REF_ACT, None])
    )
    died_batch = jnp.min(
        jnp.where(del_match, t[:, None], jnp.int32(_TIME_BIG)), axis=0
    )
    died = jnp.where(pre_del, jnp.int32(-_TIME_BIG), died_batch)

    # Each row's target element's final position.
    tgt_ctr = jnp.where(is_del, text_ops[:, K_REF_CTR], ctr_l)
    tgt_act = jnp.where(is_del, text_ops[:, K_REF_ACT], act_l)
    tmatch = (
        live[None, :]
        & (elem_ctr[None, :] == tgt_ctr[:, None])
        & (elem_act[None, :] == tgt_act[:, None])
    )
    exists = jnp.any(tmatch, axis=1)
    q = jnp.argmax(tmatch, axis=1).astype(jnp.int32)

    # visibleIndex at the row's instant: elements final-ordered before the
    # target that had appeared and not yet been tombstoned.  (Relative
    # order of coexisting elements never changes, so final-order counting
    # equals the walk's position at that time.)
    alive = live[None, :] & (born[None, :] < t[:, None]) & (died[None, :] > t[:, None])
    index0 = jnp.sum(alive & (ar[None, :] < q[:, None]), axis=1).astype(jnp.int32)

    tvalid = jnp.where(is_del, exists & (born[q] < t) & (died[q] == t), is_ins)
    tm = jnp.sum(
        mark_valid[None, :] & (mark_time[None, :] < t[:, None]), axis=1
    ).astype(jnp.int32)
    return born, died, q, index0, tvalid, tm


def _sorted_def_first(bnd_def0, mark_ops, elem_ctr, elem_act, length):
    """First-definition mark index per boundary slot: -1 for pre-batch
    defined slots, else the first mark row anchoring (start/end-writing) the
    slot, else a sentinel beyond every instant.  Interior in-range writes
    can never *first*-define a slot (they require it defined already,
    peritext.ts:243-247), so anchor writes are the whole story — and anchor
    resolution is time-independent, making this fully analytic."""
    m_ops = mark_ops.shape[0]
    c = elem_ctr.shape[0]
    two_c = 2 * c
    midx = jnp.arange(m_ops, dtype=jnp.int32)
    slots = jnp.arange(two_c, dtype=jnp.int32)

    valid, s_slot, e_slot = _batched_anchor_slots(mark_ops, elem_ctr, elem_act, length)

    WS = (valid & (s_slot < e_slot))[:, None] & (slots[None, :] == s_slot[:, None])
    WE = (valid & (e_slot < two_c))[:, None] & (slots[None, :] == e_slot[:, None])
    first = jnp.min(jnp.where(WS | WE, midx[:, None], jnp.int32(m_ops + 1)), axis=0)
    return jnp.where(bnd_def0, jnp.int32(-1), first)


# Max columns of one allowMultiple resolution group (same (type, attr-id) —
# in practice the add and removes of one comment id) the cached patch scan
# resolves exactly.  The universe checks group sizes host-side and falls
# back to the interleaved scan when exceeded, so the cap never silently
# changes results.  Read at import time, like PERITEXT_SPLICE.
PATCH_GROUP_K = int(os.environ.get("PERITEXT_PATCH_GROUP_K", "32"))


def _winner_cache_init(bnd_mask0, mark_cols, ranks, n_types, max_mark_ops, multi):
    """Per-slot per-type LWW winners of the pre-batch boundary rows.

    The patch scan's ``changed`` signal needs, per mark op, the winner of
    the op's own resolution group within the inherited set at each written
    slot (opsToMarks restricted to one group, peritext.ts:294-326).  For
    non-allowMultiple types the group is the TYPE, so the winner is a
    per-slot per-type quantity — cacheable as [2C, T, 4] (ctr, rank,
    action, attr; ctr=-1 empty) and maintainable through the scan with the
    same carry gathers _apply_mark already does.

    Resolution is ONE dominance matmul for all types at once (the
    resolve_winners trick — a [2C, M] x [M, M] MXU pass), not a per-type
    loop of [2C, M] reductions; with the padded type registry (T=16) the
    loop form materializes ~100 [2C, M] planes per replica and dominates
    the whole merge.  Winner VALUES are recovered via an index matmul
    (win @ one-hot·(index+1), exact in f32 since indices < 2^24) followed
    by [2C, T] gathers — never by summing raw field values through f32.
    Entries for allowMultiple types are unused (their groups are per-attr;
    the scan resolves them over compacted columns).
    """
    mark_ctr, mark_act, mark_action, mark_type, mark_attr = mark_cols
    m_cap = mark_ctr.shape[0]
    present = expand_mask_bits(bnd_mask0, max_mark_ops)  # [2C, M] bool
    rank = ranks[mark_act]
    type_c = jnp.clip(mark_type, 0, n_types - 1)
    nm_col = ~multi[type_c]  # non-allowMultiple columns

    same_type = mark_type[:, None] == mark_type[None, :]
    key_gt = (mark_ctr[None, :] > mark_ctr[:, None]) | (
        (mark_ctr[None, :] == mark_ctr[:, None]) & (rank[None, :] > rank[:, None])
    )
    dom = same_type & key_gt & nm_col[:, None] & nm_col[None, :]
    # dom_count[p, n] = #present dominators of column n at slot p.
    dom_count = jnp.einsum(
        "pm,nm->pn", present.astype(jnp.float32), dom.astype(jnp.float32)
    )
    win = present & nm_col[None, :] & (dom_count < 0.5)  # one-hot per (slot, type)

    onehot = (
        (type_c[:, None] == jnp.arange(n_types, dtype=jnp.int32)[None, :])
        & nm_col[:, None]
    ).astype(jnp.float32)  # [M, T]
    col_plus1 = (jnp.arange(m_cap, dtype=jnp.int32) + 1).astype(jnp.float32)
    # Precision.HIGHEST: TPU default matmul precision feeds bf16 operands
    # to the MXU, and column indices above 256 are not bf16-representable —
    # the recovered winner column would silently drift.  (The dominance
    # einsum above is safe at any precision: 0/1 operands, f32 accumulate.)
    widx = (
        jnp.round(
            jnp.matmul(
                win.astype(jnp.float32),
                onehot * col_plus1[:, None],
                precision=lax.Precision.HIGHEST,
            )
        ).astype(jnp.int32)
        - 1
    )  # [2C, T]: winner column, -1 when none
    has = widx >= 0
    wc = jnp.maximum(widx, 0)
    return jnp.where(
        has[:, :, None],
        jnp.stack(
            [mark_ctr[wc], rank[wc], mark_action[wc], mark_attr[wc]], axis=-1
        ),
        jnp.array([-1, -1, 0, 0], jnp.int32)[None, None, :],
    )  # [2C, T, 4]


def _permute_wcache(wcache, orig_idx):
    """Re-align a [2C, T, 4] winner cache after a text phase, mirroring
    _permute_boundaries: batch-born elements' slots come up empty.

    Flat single-axis slot gather (no [C, 2, T, 4] view): the view-reshaped
    gather cost the compiler SIX full-plane layout copies of the [2C, T, 4]
    cache — 1.5 GiB/launch at the bench shape, the threaded patched path's
    single largest traffic source (PROFILE_r05.md)."""
    valid, flat_src = _slot_permutation(orig_idx)
    return jnp.where(
        valid[:, None, None],
        wcache[flat_src],
        jnp.array([-1, -1, 0, 0], jnp.int32)[None, None, :],
    )


def _group_topk_cols(mark_type_col, mark_attr_col, op, k: int):
    """Indices of up to ``k`` mark-table columns in op's (type, attr) group
    (exhaustive when the host-verified group size is <= k), plus validity."""
    match = (mark_type_col == op[K_MTYPE]) & (mark_attr_col == op[K_MATTR])
    # A group can never exceed the table itself; clamping keeps an oversized
    # PERITEXT_PATCH_GROUP_K from making top_k request more lanes than exist.
    vals, cols = lax.top_k(match.astype(jnp.int32), min(k, match.shape[0]))
    return cols.astype(jnp.int32), vals > 0


def _winner_over_cand(cand, g_ctr, g_rank, g_action, g_attr):
    """LWW winner per row among candidate columns (``cand`` [N, K] bool with
    per-column key/value vectors [K]).  The shared reduction core of
    _winner_over_cols and the compact-delta scan's group resolution — one
    definition, so the two patched paths cannot diverge on tie-breaks."""
    neg = jnp.int32(-(2**31) + 1)
    ctrs = jnp.where(cand, g_ctr[None, :], neg)
    max_ctr = jnp.max(ctrs, axis=1)
    tie = cand & (g_ctr[None, :] == max_ctr[:, None])
    rks = jnp.where(tie, g_rank[None, :], neg)
    max_rank = jnp.max(rks, axis=1)
    win = tie & (g_rank[None, :] == max_rank[:, None])
    has = cand.any(axis=1)
    w_action = jnp.sum(jnp.where(win, g_action[None, :], 0), axis=1)
    w_attr = jnp.sum(jnp.where(win, g_attr[None, :], 0), axis=1)
    return (
        jnp.where(has, max_ctr, jnp.int32(-1)),
        jnp.where(has, max_rank, jnp.int32(-1)),
        w_action,
        w_attr,
        has,
    )


def _winner_over_cols(carry, cols, col_ok, mark_cols, ranks):
    """LWW winner per slot among the given table columns present in the
    carry rows: [2C, K] work instead of [2C, M]."""
    mark_ctr, mark_act, mark_action, _mark_type, mark_attr = mark_cols
    words = (cols // MASK_WORD_BITS).astype(jnp.int32)
    bits = (cols % MASK_WORD_BITS).astype(jnp.uint32)
    pres = (jnp.take(carry, words, axis=1) >> bits[None, :]) & jnp.uint32(1)
    cand = pres.astype(bool) & col_ok[None, :]  # [2C, K]
    return _winner_over_cand(
        cand, mark_ctr[cols], ranks[mark_act[cols]], mark_action[cols], mark_attr[cols]
    )


def _delta_mark_scan(
    bnd_mask_base,
    wcache0,
    mark_ops,
    mark_time,
    mcols_final,
    elem_ctr,
    elem_act,
    length,
    born,
    died,
    def_first,
    src_ok,
    src_c,
    tm,
    mark_count0,
    ranks,
    multi,
    group_k: int,
    has_multi: bool,
    t_act: int,
    perm=None,
):
    """Compact-delta mark-row scan (the default patched path).

    Emits per-step patch records identical to the dense scan in
    merge_step_sorted_patched — the differential bar is byte-identical
    assembled Patch streams AND byte-identical post-merge planes — but the
    full boundary plane is read once and written once per launch, and the
    winner cache moves through the scan with slot-local writes instead of
    full-plane selects:

    - ``root_src`` [2C] i32: which slot's PRE-batch row is the full-width
      base of each slot's current row (-1: zero row).  An anchor write
      (the rebase write class on _apply_mark) copies its carry source's
      *pointer* instead of its [W]-word row; pre-batch bits are recovered
      by composed index reads into the untouched ``bnd_mask0`` plane.
    - ``win_bits`` [2C, w_act] u32: the active word WINDOW of every row
      (the only words the batch's new bits can land in — the same window
      rule as _apply_marks_batch).  In-range bit ORs are one-word-column
      read-modify-writes; anchor writes copy one row.
    - ``bw`` [T, 2C] i32: the winning BATCH table column per (type, slot)
      among this batch's non-allowMultiple ops so far (-1: none).  The
      dense scan's carried ``[2C, T, 4]`` cache value at any slot is
      exactly ``LWW(wcache0[root_src[slot]], entry(bw[:, slot]))`` — max
      over (ctr, rank) is associative, so recording every in-range batch
      op in ``bw`` and composing against the untouched base cache gives
      byte-identical winners without gating on the composed current value.
      Anchor writes copy one ``[T]`` column (plus the root pointer); the
      full ``[2C, T, 4]`` cache plane is read once (composed gathers) and
      written once by the post-scan compose, never carried.
    - ``acc_root``/``acc_win``: the insert rows' inherited-row composition
      captured at their instants (composed to full [Lt, W] rows after the
      scan).

    The gated writes use the write-unconditionally/select-the-VALUE shape
    (``col: where(gate, new, cur); plane: dus(plane, col)``) so XLA keeps
    the carried buffers in place — ``where(gate, dus(..), plane)`` costs a
    full-plane copy per step.  allowMultiple group resolution only
    compiles when the batch actually carries multi ops (``has_multi``),
    at the host-census-measured width ``group_k`` ≤ PATCH_GROUP_K.
    ``t_act`` (static, the registry-size pow2 bucket ≤ MAX_MARK_TYPES)
    sizes the carried batch-winner table's type axis: valid ops' type ids
    are < NUM_MARK_TYPES ≤ t_act, so the dead padding types — the cache
    plane is padded to MAX_MARK_TYPES so registration never recompiles —
    drop out of the per-step traversal; base-plane types ≥ t_act pass
    through the final compose untouched.
    """
    mark_ctr_f, mark_act_f, mark_action_f, mark_type_f, mark_attr_f = mcols_final
    c = elem_ctr.shape[0]
    two_c = 2 * c
    m_ops = mark_ops.shape[0]
    w_words = bnd_mask_base.shape[-1]
    n_types = multi.shape[0]
    slots = jnp.arange(two_c, dtype=jnp.int32)
    ar_c = jnp.arange(c, dtype=jnp.int32)
    live_c = ar_c < length
    empty_wc = jnp.array([-1, -1, 0, 0], jnp.int32)
    type_ar = jnp.arange(t_act, dtype=jnp.int32)

    valid, s_slots, e_slots = _batched_anchor_slots(
        mark_ops, elem_ctr, elem_act, length
    )
    m_idx0 = jnp.arange(m_ops, dtype=jnp.int32)

    # Window geometry (same rule as _apply_marks_batch; valid rows are a
    # prefix, so op m's table column is mark_count0 + m).
    w_act = min((m_ops + MASK_WORD_BITS - 1) // MASK_WORD_BITS + 1, w_words)
    w0 = jnp.clip(mark_count0 // MASK_WORD_BITS, 0, w_words - w_act)
    word_ar = jnp.arange(w_act, dtype=jnp.int32)
    bit_off = mark_count0 + m_idx0 - w0 * MASK_WORD_BITS  # [M] window-relative
    op_rank_v = ranks[mark_ops[:, K_ACT]]
    tau_v = jnp.clip(mark_ops[:, K_MTYPE], 0, t_act - 1)
    is_multi_v = multi[tau_v]

    # The text phase's boundary permutation composes INTO every base-plane
    # read (the _apply_marks_batch `perm=` trick): with ``perm`` given,
    # ``bnd_mask_base`` AND ``wcache0`` are the RAW pre-splice planes and
    # no permuted [2C, W] / [2C, T, 4] copy is ever materialized — both
    # planes are only read through composed gathers and written once by
    # the final composes.
    if perm is not None:
        pvalid, pflat = perm

        def base_rows(idx, ok):  # post-splice slots -> full-width base rows
            okc = ok & pvalid[idx]
            return jnp.where(
                okc[:, None],
                bnd_mask_base[pflat[idx]],
                jnp.uint32(0),
            )

        def base_words(idx, ok, words):  # [N] slots x [K] words -> [N, K]
            okc = ok & pvalid[idx]
            return jnp.where(
                okc[:, None],
                bnd_mask_base[pflat[idx][:, None], words[None, :]],
                jnp.uint32(0),
            )

        def base_wc_rows(idx, ok):  # slots -> [N, T, 4] base cache rows
            okc = ok & pvalid[idx]
            return jnp.where(
                okc[:, None, None],
                wcache0[pflat[idx]],
                empty_wc[None, None, :],
            )

        def base_wc_tau(idx, ok, t):  # slots -> [N, 4] entries at type t
            okc = ok & pvalid[idx]
            return jnp.where(
                okc[:, None], wcache0[pflat[idx], t], empty_wc[None, :]
            )

    else:

        def base_rows(idx, ok):
            return jnp.where(ok[:, None], bnd_mask_base[idx], jnp.uint32(0))

        def base_words(idx, ok, words):
            return jnp.where(
                ok[:, None],
                bnd_mask_base[idx[:, None], words[None, :]],
                jnp.uint32(0),
            )

        def base_wc_rows(idx, ok):
            return jnp.where(
                ok[:, None, None], wcache0[idx], empty_wc[None, None, :]
            )

        def base_wc_tau(idx, ok, t):
            return jnp.where(ok[:, None], wcache0[idx, t], empty_wc[None, :])

    # Carry-independent signals, hoisted OUT of the scan and computed in
    # one batched pass over the op axis (identical per-op semantics: the
    # same _walk_signals definition, vmapped).  The scan body keeps only
    # the carry-dependent work (`changed` + the plane updates).
    defined_all = def_first[None, :] < m_idx0[:, None]  # [M, 2C]
    visible_all = (
        live_c[None, :]
        & (born[None, :] < mark_time[:, None])
        & (died[None, :] > mark_time[:, None])
    )  # [M, C]
    written_all, during_all, vis_all, final_vis_all = jax.vmap(
        lambda s, e, d, v: _walk_signals((s, e, slots, d, None, None), v, c)
    )(s_slots, e_slots, defined_all, visible_all)
    src_q_all = lax.cummax(
        jnp.where(defined_all, slots[None, :], jnp.int32(-1)), axis=1
    )  # [M, 2C]

    def compose_rows(root, win_rows):
        """(root pointer [N], window words [N, w_act]) -> full [N, W] rows:
        one gather into the untouched base plane + the w_act static
        broadcast-selects of the window write-back (PROFILE_r05 step 3)."""
        base = base_rows(jnp.maximum(root, 0), root >= 0)
        word_full = jnp.arange(w_words, dtype=jnp.int32)
        out = base
        for j in range(w_act):
            out = jnp.where(
                word_full[None, :] == w0 + j, win_rows[:, j][:, None], out
            )
        return out

    carry0 = (
        slots,  # root_src: every slot starts as its own base row
        base_words(slots, slots >= 0, w0 + word_ar),
        jnp.full((t_act, two_c), -1, jnp.int32),  # bw: no batch winner yet
        jnp.full(src_c.shape[0], -1, jnp.int32),  # acc_root
        jnp.zeros((src_c.shape[0], w_act), jnp.uint32),  # acc_win
    )
    xs = (
        mark_ops,
        m_idx0,
        s_slots,
        e_slots,
        valid,
        bit_off,
        op_rank_v,
        tau_v,
        is_multi_v,
        during_all,
        src_q_all,
    )

    def bw_entry(colv):
        """Batch-winner table columns -> (ctr, rank, action, attr) entries
        ([-1, -1, 0, 0] where no batch winner); columns >= mark_count0 are
        exactly this batch's ops, so the final mark table holds them."""
        ok = colv >= 0
        cc = jnp.maximum(colv, 0)
        return jnp.stack(
            [
                jnp.where(ok, mark_ctr_f[cc], jnp.int32(-1)),
                jnp.where(ok, ranks[mark_act_f[cc]], jnp.int32(-1)),
                jnp.where(ok, mark_action_f[cc], jnp.int32(0)),
                jnp.where(ok, mark_attr_f[cc], jnp.int32(0)),
            ],
            axis=-1,
        )

    def lww(a, b):
        """Pick b where it beats a on (ctr, rank) — the dense scan's
        `beats` rule, applied entrywise to [..., 4] cache entries."""
        pick = (b[..., 0] > a[..., 0]) | (
            (b[..., 0] == a[..., 0]) & (b[..., 1] > a[..., 1])
        )
        return jnp.where(pick[..., None], b, a)

    def step(carry, xs_t):
        root_src, win_bits, bw, acc_root, acc_win = carry
        (op, m_idx, s_sl, e_sl, val, bo, op_rank, tau, is_mop,
         during, src_q) = xs_t
        wb = bo // MASK_WORD_BITS
        bit_u = jnp.uint32(1) << (bo % MASK_WORD_BITS).astype(jnp.uint32)
        defined = def_first < m_idx

        # Inserts whose instant lands at this plane version capture their
        # inherited row's composition BEFORE this mark writes (same
        # read-at-step-start as the dense scan; pad steps never write, so
        # any tm landing on a pad index still reads the right version).
        take = src_ok & (tm == m_idx)
        acc_root = jnp.where(take, root_src[src_c], acc_root)
        acc_win = jnp.where(take[:, None], win_bits[src_c], acc_win)

        # `changed`: the op's group winner within the inherited set at each
        # slot's carry source, composed on the fly — the untouched base
        # cache gathered at the source's ROOT, LWW'd against the carried
        # batch-winner column — a few [2C] gathers where the dense scan
        # materialized a full [2C, T, 4] carry select.
        q_ok = src_q >= 0
        qc = jnp.maximum(src_q, 0)
        rootq = jnp.where(q_ok, root_src[qc], jnp.int32(-1))
        rq_ok = rootq >= 0
        rqc = jnp.maximum(rootq, 0)
        bw_tau = lax.dynamic_slice(bw, (tau, 0), (1, two_c))[0]  # [2C]
        base_e = base_wc_tau(rqc, rq_ok, tau)
        wnm = lww(
            base_e, bw_entry(jnp.where(q_ok, bw_tau[qc], jnp.int32(-1)))
        )  # [2C, 4]
        w_ctr, w_rank = wnm[:, 0], wnm[:, 1]
        w_action, w_attr = wnm[:, 2], wnm[:, 3]
        has_winner = w_ctr >= 0

        if has_multi:
            # allowMultiple groups resolve over their (host-gated, host-
            # sized) compacted columns; presence composes window words from
            # the carry with non-window words from the untouched base plane
            # at the row's root (rootq/rqc shared with the `changed` read).
            cols, col_ok = _group_topk_cols(mark_type_f, mark_attr_f, op, group_k)
            words = (cols // MASK_WORD_BITS).astype(jnp.int32)
            bits = (cols % MASK_WORD_BITS).astype(jnp.uint32)
            in_win = (words >= w0) & (words < w0 + w_act)
            win_part = jnp.take(
                win_bits[qc], jnp.clip(words - w0, 0, w_act - 1), axis=1
            )
            base_part = base_words(rqc, rq_ok, words)
            word_val = jnp.where(
                q_ok[:, None],
                jnp.where(in_win[None, :], win_part, base_part),
                jnp.uint32(0),
            )
            pres = ((word_val >> bits[None, :]) & jnp.uint32(1)).astype(bool)
            g_ctr, g_rank, g_action, g_attr, g_has = _winner_over_cand(
                pres & col_ok[None, :],
                mark_ctr_f[cols],
                ranks[mark_act_f[cols]],
                mark_action_f[cols],
                mark_attr_f[cols],
            )
            w_ctr = jnp.where(is_mop, g_ctr, w_ctr)
            w_rank = jnp.where(is_mop, g_rank, w_rank)
            w_action = jnp.where(is_mop, g_action, w_action)
            w_attr = jnp.where(is_mop, g_attr, w_attr)
            has_winner = jnp.where(is_mop, g_has, has_winner)

        changed = _changed_vs_winner(
            op, op_rank, w_ctr, w_rank, w_action, w_attr, has_winner
        )

        # --- apply the op to the carry ---------------------------------
        # All write values read the PRE-update carry (the dense scan's
        # writes are simultaneous); every update is an ELEMENTWISE select
        # keyed on slot one-hots, so XLA fuses the whole chain into one
        # traversal of each carried plane per step.  (The batched-index
        # dynamic-update-slice formulation lowers to near-serial scatters
        # under vmap on CPU and to per-replica sub-loops on TPU — measured
        # strictly worse on both.)
        s_lt_e = s_sl < e_sl
        write_s = val & s_lt_e
        write_e = val & (e_sl < two_c)
        e_cl = jnp.minimum(e_sl, jnp.int32(two_c - 1))
        q_s = src_q[s_sl]
        q_e = src_q[e_cl]
        root_s_v = jnp.where(q_s >= 0, root_src[jnp.maximum(q_s, 0)], jnp.int32(-1))
        root_e_v = jnp.where(q_e >= 0, root_src[jnp.maximum(q_e, 0)], jnp.int32(-1))
        win_row_s = jnp.where(q_s >= 0, win_bits[jnp.maximum(q_s, 0)], jnp.uint32(0))
        win_row_e = jnp.where(q_e >= 0, win_bits[jnp.maximum(q_e, 0)], jnp.uint32(0))
        col_s = jnp.where(q_s >= 0, bw[:, jnp.maximum(q_s, 0)], jnp.int32(-1))
        col_e = jnp.where(q_e >= 0, bw[:, jnp.maximum(q_e, 0)], jnp.int32(-1))
        one_s = (slots == s_sl) & write_s
        one_e = (slots == e_cl) & write_e
        inr_def = during & defined & val

        # Window words: in-range bit OR (only the op's word can change) +
        # the two anchor-row rebases.
        bit_at = inr_def[:, None] & (word_ar == wb)[None, :]
        win_bits = jnp.where(bit_at, win_bits | bit_u, win_bits)
        bit_row = jnp.where(word_ar == wb, bit_u, jnp.uint32(0))
        win_bits = jnp.where(one_s[:, None], (win_row_s | bit_row)[None, :], win_bits)
        win_bits = jnp.where(one_e[:, None], win_row_e[None, :], win_bits)
        root_src = jnp.where(one_s, root_s_v, root_src)
        root_src = jnp.where(one_e, root_e_v, root_src)

        # Batch-winner table: record the op's column into its own type's
        # row over in-range defined slots where it beats the current BATCH
        # winner (non-allowMultiple only — the dense `beats_nm` update
        # class; gating on the composed-with-base value is unnecessary,
        # max over (ctr, rank) is associative and the final compose takes
        # the same max), then the two anchor-COLUMN rebases.
        cur = bw_entry(bw_tau)
        beats = (bw_tau < 0) | (op[K_CTR] > cur[:, 0]) | (
            (op[K_CTR] == cur[:, 0]) & (op_rank > cur[:, 1])
        )
        tau_oh = type_ar == tau
        upd_inr = inr_def & ~is_mop & beats
        op_col = mark_count0 + m_idx
        bw = jnp.where(upd_inr[None, :] & tau_oh[:, None], op_col, bw)
        cs_tau = col_s[tau]
        cs = bw_entry(cs_tau[None])[0]
        s_beats = (cs_tau < 0) | (op[K_CTR] > cs[0]) | (
            (op[K_CTR] == cs[0]) & (op_rank > cs[1])
        )
        new_col = jnp.where(~is_mop & s_beats, op_col, cs_tau)
        col_s = jnp.where(tau_oh, new_col, col_s)
        bw = jnp.where(one_s[None, :], col_s[:, None], bw)
        bw = jnp.where(one_e[None, :], col_e[:, None], bw)

        return (root_src, win_bits, bw, acc_root, acc_win), changed & val

    (root_src_f, win_f, bw_f, acc_root, acc_win), changed_all = lax.scan(
        step, carry0, xs
    )
    mrec = {
        "written": written_all & valid[:, None],
        "during": during_all & valid[:, None],
        "changed": changed_all,
        "vis": vis_all,
        "obj_len": final_vis_all,
    }

    # Inserts after every mark instant read the final composition.
    take_f = src_ok & (tm == m_ops)
    acc_root = jnp.where(take_f, root_src_f[src_c], acc_root)
    acc_win = jnp.where(take_f[:, None], win_f[src_c], acc_win)
    ins_mask = compose_rows(acc_root, acc_win)

    # Final planes: ONE composed gather over the untouched base plane +
    # the window write-back; definedness is fully analytic (anchor writes
    # are the only first definitions, _sorted_def_first); the final winner
    # cache composes the same way — base rows gathered at each slot's
    # root, LWW'd against the batch winners — the launch's only full
    # [2C, T, 4] read + write.
    new_mask = compose_rows(root_src_f, win_f)
    new_def = def_first <= m_ops
    base_wc = base_wc_rows(jnp.maximum(root_src_f, 0), root_src_f >= 0)
    bw_vals = jnp.swapaxes(bw_entry(bw_f), 0, 1)  # [2C, t_act, 4]
    wcache_f = jnp.concatenate(
        [lww(base_wc[:, :t_act], bw_vals), base_wc[:, t_act:]], axis=1
    )
    return new_def, new_mask, ins_mask, mrec, wcache_f


def merge_step_sorted_patched(
    state: DocState,
    text_ops: jax.Array,
    round_of: jax.Array,
    num_rounds: jax.Array,
    mark_ops: jax.Array,
    ranks: jax.Array,
    char_buf: jax.Array,
    multi: jax.Array,
    text_time: jax.Array,
    mark_time: jax.Array,
    maxk: int,
    has_marks: bool = True,
    wcache_in: jax.Array | None = None,
    mode: str = "delta",
    group_k: int | None = None,
    has_multi: bool = True,
    t_act: int | None = None,
    readback: str = "planes",
    span_cap: int = 8,
    cand_cap: int = 64,
    vis_base: jax.Array | None = None,
    vis_after: jax.Array | None = None,
):
    """Sorted merge that also emits per-op patch records.

    Produces the exact interleaved-path records (apply_ops_patched) for the
    same delivery order — differential bar: byte-identical assembled Patch
    streams (tests/test_engine_patches, tests/test_sorted_merge) — while
    the text phase runs in O(depth) placement rounds and the scan covers
    only the batch's mark rows.  ``text_time`` / ``mark_time`` are each
    row's flat delivery-stream position (encode row_pos; a fused run's
    first char), padded with a beyond-any-instant sentinel.

    ``wcache_in`` (optional [2C, T, 4], PRE-placement slot coordinates):
    the persisted per-slot per-type winner cache from the previous patched
    merge — the universe threads it between ingests so the [2C, M]
    dominance init amortizes to ONE launch per universe lifetime in an
    all-patched (editor-fleet) workload.  It is derived state: exactly the
    cache a fresh init over the same boundary rows would produce
    (tests assert this), permuted alongside the boundary planes here.
    Returns ``(new_state, records)``; records carry ``wcache`` (final,
    post-batch coordinates) for the universe to persist — except on the
    cacheless mark-free path, which neither needs nor produces one.

    ``mode`` selects the mark-row scan's carry representation: "delta"
    (default) runs the compact-delta scan (_delta_mark_scan — composition
    pointers + window words carried; the full [2C, W] / [2C, T, 4] planes
    read once and written once per launch), "dense" the original
    full-plane-carry scan below.  Both are byte-identical in records and
    state; PERITEXT_PATCH_PATH=dense forces the dense variant for A/B.
    ``group_k``/``has_multi`` statically specialize the delta scan's
    allowMultiple group resolution from the host census.

    ``readback`` selects the record *transfer* format (orthogonal to the
    scan-carry ``mode``): "planes" returns the full per-slot mark planes
    (today's path, the A/B baseline), "compact" reduces them on device to
    ``[M, span_cap]`` run tables via :func:`compact_mark_records` and
    drops host-redundant fields (``kind`` — the encoded text rows already
    carry it), so the D2H readback is proportional to the emitted patches
    instead of the document.  ``mcount`` carries the true span count; the
    universe falls back to a planes launch when any row overflows
    ``span_cap``, so both formats always assemble byte-identical streams.
    ``cand_cap`` statically sizes the compaction's defined-slot candidate
    axis from the host's mark-count mirror (defined slots never exceed 2x
    the mark table — see compact_mark_records).

    ``vis_base``/``vis_after`` (traced scalars; None = whole-table merge)
    re-anchor the record coordinates when the merge runs over a gathered
    WINDOW of the document (the frontier-bounded path): every visibleIndex
    the records carry is window-local, and the true index adds the count
    of visible elements before the window (``vis_base``); the instant's
    objLength adds the visible elements on both sides (text edits all land
    inside the window, so both counts are batch-invariant).  The offsets
    apply BEFORE span compaction so the finishPartialPatch filters and end
    clamps run on global coordinates — byte-identical records to the
    full-table merge on either readback format.
    """

    def _finish_records(records, cand_def):
        if vis_base is not None:
            records = dict(records)
            records["index0"] = records["index0"] + vis_base
            records["vis"] = records["vis"] + vis_base
            records["obj_len"] = records["obj_len"] + vis_base + vis_after
        if readback != "compact":
            return records
        if cand_def is None:
            # Mark-free fast path: no mark rows, hence no spans — the run
            # tables are statically empty.
            m_pad = records["written"].shape[0]
            run_start = jnp.zeros((m_pad, span_cap), jnp.int32)
            run_end = jnp.zeros((m_pad, span_cap), jnp.int32)
            count = jnp.zeros((m_pad,), jnp.int32)
        else:
            run_start, run_end, count = compact_mark_records(
                records["written"], records["during"], records["changed"],
                records["vis"], records["obj_len"], cand_def, span_cap, cand_cap,
            )
        out = {
            "tvalid": records["tvalid"],
            "index0": records["index0"],
            "ins_mask": records["ins_mask"],
            "mstart": run_start,
            "mend": run_end,
            "mcount": count,
        }
        if "wcache" in records:
            out["wcache"] = records["wcache"]
        return out

    elem_ctr, elem_act, deleted, chars, orig_idx, length = place_text_batch(
        state.elem_ctr,
        state.elem_act,
        state.deleted,
        state.chars,
        state.length,
        text_ops,
        round_of,
        num_rounds,
        ranks,
        char_buf,
        maxk,
    )
    pvalid_p, pflat_p = _slot_permutation(orig_idx)
    bnd_def0 = jnp.where(pvalid_p, state.bnd_def[pflat_p], False)
    # The compact-delta warm path never materializes the permuted mask
    # plane: the scan reads the RAW plane through the composed permutation
    # and writes the final plane once (its compose).  Every other path
    # (dense, mark-free, and the cold dominance init, which expands the
    # full plane anyway) materializes it here, exactly as before.
    delta_composed = mode == "delta" and has_marks and wcache_in is not None
    bnd_mask0 = (
        None
        if delta_composed
        else jnp.where(pvalid_p[:, None], state.bnd_mask[pflat_p], jnp.uint32(0))
    )
    mark_valid = mark_ops[:, K_KIND] == KIND_MARK
    born, died, q, index0, tvalid, tm = _sorted_text_records(
        elem_ctr, elem_act, orig_idx, length, state.deleted,
        text_ops, text_time, mark_time, mark_valid,
    )

    # Inherited-marks source per insert row (getActiveMarksAtIndex,
    # peritext.ts:328-330): nearest slot left of the insertion gap that is
    # defined at the row's instant.  All chars of a delivery-adjacent run
    # share it (the run's own fresh slots are undefined).
    c = elem_ctr.shape[0]
    slots = jnp.arange(2 * c, dtype=jnp.int32)
    def_first = _sorted_def_first(bnd_def0, mark_ops, elem_ctr, elem_act, length)
    kind_t = text_ops[:, K_KIND]
    is_ins = (kind_t == KIND_INSERT) | (kind_t == KIND_INSERT_RUN)
    src = jnp.max(
        jnp.where(
            (def_first[None, :] < tm[:, None]) & (slots[None, :] < 2 * q[:, None]),
            slots[None, :],
            jnp.int32(-1),
        ),
        axis=1,
    )
    src_ok = (src >= 0) & is_ins
    src_c = jnp.maximum(src, 0)

    # Mark table appended up front: the scan resolves winners against final
    # columns, with per-step mark_count restricting candidates to ops
    # already applied (present bits can't contain later ops anyway).
    mark_cols = _append_mark_table(
        (state.mark_ctr, state.mark_act, state.mark_action, state.mark_type, state.mark_attr),
        mark_ops,
        state.mark_count,
        state.max_mark_ops,
    )
    mark_ctr_f, mark_act_f, mark_action_f, mark_type_f, mark_attr_f, mark_count_f = mark_cols

    w = state.bnd_mask.shape[-1]
    acc0 = jnp.zeros((text_ops.shape[0], w), jnp.uint32)
    m_idx0 = jnp.arange(mark_ops.shape[0], dtype=jnp.int32)

    # Per-slot per-type winner cache: the scan's `changed` signal resolves
    # the op's group winner from this [2C, T, 4] cache (non-allowMultiple)
    # or a K-compacted column subset (allowMultiple; host-gated to the
    # interleaved fallback when a group exceeds PATCH_GROUP_K) instead of
    # expanding a [2C, M] presence plane per step — the patched path's
    # dominant traffic (PROFILE_r04.md item 3).
    mcols_final = (mark_ctr_f, mark_act_f, mark_action_f, mark_type_f, mark_attr_f)
    n_types = multi.shape[0]

    if not has_marks:
        # Static no-marks fast path (the common pure-typing batch, chosen
        # by the universe from the encoded rows): boundary planes never
        # evolve, so inserts inherit straight from the pre-scan planes and
        # the winner-cache init + mark scan compile away entirely.
        rows0 = bnd_mask0[src_c]
        ins_mask = jnp.where((src_ok)[:, None], rows0, jnp.uint32(0))
        m_pad = mark_ops.shape[0]
        new_state = DocState(
            elem_ctr=elem_ctr,
            elem_act=elem_act,
            deleted=deleted,
            chars=chars,
            bnd_def=bnd_def0,
            bnd_mask=bnd_mask0,
            mark_ctr=mark_ctr_f,
            mark_act=mark_act_f,
            mark_action=mark_action_f,
            mark_type=mark_type_f,
            mark_attr=mark_attr_f,
            length=length,
            mark_count=mark_count_f,
        )
        records = {
            "kind": kind_t,
            "tvalid": tvalid,
            "index0": index0,
            "ins_mask": ins_mask,
            "written": jnp.zeros((m_pad, 2 * c), bool),
            "during": jnp.zeros((m_pad, 2 * c), bool),
            "changed": jnp.zeros((m_pad, 2 * c), bool),
            "vis": jnp.zeros((m_pad, 2 * c), jnp.int32),
            "obj_len": jnp.zeros((m_pad,), jnp.int32),
        }
        if wcache_in is not None:
            # Rows didn't evolve; the persisted cache stays valid once
            # realigned to the new slot coordinates.
            records["wcache"] = _permute_wcache(wcache_in, orig_idx)
        return new_state, _finish_records(records, None)

    # The compact-delta warm path also never materializes the permuted
    # winner cache: the scan reads the cache only through gathers, so the
    # slot permutation composes into them exactly as the boundary plane's
    # does, and the [2C, T, 4] permute copy disappears from the launch.
    if delta_composed:
        wcache0 = wcache_in
    else:
        wcache0 = (
            _permute_wcache(wcache_in, orig_idx)
            if wcache_in is not None
            else _winner_cache_init(
                bnd_mask0, mcols_final, ranks, n_types, state.max_mark_ops, multi
            )
        )

    if mode == "delta":
        # Compact-delta mark-row scan: the carry holds only the batch's
        # composition state; the full [2C, W] / [2C, T, 4] planes are read
        # once and written once per launch (see _delta_mark_scan).
        bnd_def, bnd_mask, ins_mask, mrec, wcache_f = _delta_mark_scan(
            state.bnd_mask if delta_composed else bnd_mask0,
            wcache0,
            mark_ops,
            mark_time,
            mcols_final,
            elem_ctr,
            elem_act,
            length,
            born,
            died,
            def_first,
            src_ok,
            src_c,
            tm,
            state.mark_count,
            ranks,
            multi,
            group_k if group_k is not None else PATCH_GROUP_K,
            has_multi,
            t_act if t_act is not None else n_types,
            perm=(pvalid_p, pflat_p) if delta_composed else None,
        )
        new_state = DocState(
            elem_ctr=elem_ctr,
            elem_act=elem_act,
            deleted=deleted,
            chars=chars,
            bnd_def=bnd_def,
            bnd_mask=bnd_mask,
            mark_ctr=mark_ctr_f,
            mark_act=mark_act_f,
            mark_action=mark_action_f,
            mark_type=mark_type_f,
            mark_attr=mark_attr_f,
            length=length,
            mark_count=mark_count_f,
        )
        records = {
            "kind": kind_t,
            "tvalid": tvalid,
            "index0": index0,
            "ins_mask": ins_mask,
            "written": mrec["written"],
            "during": mrec["during"],
            "changed": mrec["changed"],
            "vis": mrec["vis"],
            "obj_len": mrec["obj_len"],
            "wcache": wcache_f,
        }
        return new_state, _finish_records(records, bnd_def)

    ar_c = jnp.arange(c, dtype=jnp.int32)
    empty_wc = jnp.array([-1, -1, 0, 0], jnp.int32)

    def step(carry, xs):
        bnd_def, bnd_mask, acc, wcache = carry
        op, m_idx, t_m = xs
        # Inserts whose instant lands at this plane version read their
        # inherited row before this mark writes.  (Valid mark rows are a
        # prefix; pad steps leave the planes untouched, so any tm landing
        # on a pad index still reads the right version.)
        rows = bnd_mask[src_c]  # [Lt, W]
        take = src_ok & (tm == m_idx)
        acc = acc | jnp.where(take[:, None], rows, jnp.uint32(0))

        # Synthetic state view: final text plane with visibility masked to
        # this instant, evolving boundary planes, final mark table.
        st_deleted = ~((born < t_m) & (died > t_m))
        st = DocState(
            elem_ctr=elem_ctr,
            elem_act=elem_act,
            deleted=st_deleted,
            chars=chars,
            bnd_def=bnd_def,
            bnd_mask=bnd_mask,
            mark_ctr=mark_ctr_f,
            mark_act=mark_act_f,
            mark_action=mark_action_f,
            mark_type=mark_type_f,
            mark_attr=mark_attr_f,
            length=length,
            mark_count=state.mark_count + m_idx,
        )
        valid = op[K_KIND] == KIND_MARK
        ctx = _mark_slot_context(st, op)
        carry_rows, src = ctx[4], ctx[5]
        written, during, vis, final_vis = _walk_signals(
            ctx, (ar_c < length) & ~st_deleted, c
        )

        # `changed`: winner of the op's resolution group within the
        # inherited set, from the cache (LWW-per-type) or the compacted
        # group columns (allowMultiple).
        src_ok_slot = src >= 0
        srcc = jnp.maximum(src, 0)
        wc_carry = jnp.where(
            src_ok_slot[:, None, None], wcache[srcc], empty_wc[None, None, :]
        )  # [2C, T, 4]
        wnm = jnp.take(wc_carry, jnp.clip(op[K_MTYPE], 0, n_types - 1), axis=1)
        cols, col_ok = _group_topk_cols(mark_type_f, mark_attr_f, op, PATCH_GROUP_K)
        g_ctr, g_rank, g_action, g_attr, g_has = _winner_over_cols(
            carry_rows, cols, col_ok, mcols_final, ranks
        )
        is_multi_op = multi[jnp.clip(op[K_MTYPE], 0, n_types - 1)]
        w_ctr = jnp.where(is_multi_op, g_ctr, wnm[:, 0])
        w_rank = jnp.where(is_multi_op, g_rank, wnm[:, 1])
        w_action = jnp.where(is_multi_op, g_action, wnm[:, 2])
        w_attr = jnp.where(is_multi_op, g_attr, wnm[:, 3])
        has_winner = jnp.where(is_multi_op, g_has, wnm[:, 0] >= 0)

        op_rank = ranks[op[K_ACT]]
        changed = _changed_vs_winner(
            op, op_rank, w_ctr, w_rank, w_action, w_attr, has_winner
        )

        new_st = _apply_mark_ctx(st, op, ctx)
        bnd_def = jnp.where(valid, new_st.bnd_def, bnd_def)
        bnd_mask = jnp.where(valid, new_st.bnd_mask, bnd_mask)

        # Cache maintenance mirrors _apply_mark's write classes: written
        # slots take their carry's winners, with the op merged into its own
        # type's entry where its bit lands (in-range) and it beats the
        # carried winner.  allowMultiple ops join rows but never affect a
        # per-type LWW entry.
        in_range = during
        write = written
        t_oh = jnp.arange(n_types, dtype=jnp.int32) == op[K_MTYPE]
        beats_nm = (wnm[:, 0] < 0) | (op[K_CTR] > wnm[:, 0]) | (
            (op[K_CTR] == wnm[:, 0]) & (op_rank > wnm[:, 1])
        )
        op_vals = jnp.stack(
            [op[K_CTR], op_rank, op[K_MACTION], op[K_MATTR]]
        ).astype(jnp.int32)
        upd = jnp.where(
            t_oh[None, :, None]
            & ((~is_multi_op) & in_range & beats_nm)[:, None, None],
            op_vals[None, None, :],
            wc_carry,
        )
        wcache = jnp.where((write & valid)[:, None, None], upd, wcache)

        rec = {
            "written": written & valid,
            "during": during & valid,
            "changed": changed & valid,
            "vis": vis,
            "obj_len": final_vis,
        }
        return (bnd_def, bnd_mask, acc, wcache), rec

    (bnd_def, bnd_mask, acc, wcache_f), mrec = lax.scan(
        step, (bnd_def0, bnd_mask0, acc0, wcache0), (mark_ops, m_idx0, mark_time)
    )
    # Inserts after every mark instant read the final planes.
    rows = bnd_mask[src_c]
    take = src_ok & (tm == mark_ops.shape[0])
    ins_mask = acc | jnp.where(take[:, None], rows, jnp.uint32(0))

    new_state = DocState(
        elem_ctr=elem_ctr,
        elem_act=elem_act,
        deleted=deleted,
        chars=chars,
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=mark_ctr_f,
        mark_act=mark_act_f,
        mark_action=mark_action_f,
        mark_type=mark_type_f,
        mark_attr=mark_attr_f,
        length=length,
        mark_count=mark_count_f,
    )
    records = {
        "kind": kind_t,
        "tvalid": tvalid,
        "index0": index0,
        "ins_mask": ins_mask,
        "written": mrec["written"],
        "during": mrec["during"],
        "changed": mrec["changed"],
        "vis": mrec["vis"],
        "obj_len": mrec["obj_len"],
        # Post-batch winner cache, persisted by the universe so the next
        # patched merge skips the dominance init.
        "wcache": wcache_f,
    }
    return new_state, _finish_records(records, bnd_def)


@functools.lru_cache(maxsize=None)
def _merge_step_sorted_patched_batch(
    maxk: int,
    has_marks: bool,
    has_wcache: bool,
    mode: str,
    group_k: int | None,
    has_multi: bool,
    t_act: int | None,
    readback: str,
    span_cap: int,
    cand_cap: int,
):
    kw = dict(
        maxk=maxk, has_marks=has_marks, mode=mode, group_k=group_k,
        has_multi=has_multi, t_act=t_act, readback=readback, span_cap=span_cap,
        cand_cap=cand_cap,
    )
    if has_wcache:
        def call(st, t, ro, nr, m, rk, b, mu, tt, mt, wc):
            return merge_step_sorted_patched(
                st, t, ro, nr, m, rk, b, mu, tt, mt, wcache_in=wc, **kw
            )

        return jax.jit(
            jax.vmap(call, in_axes=(0, 0, 0, None, 0, None, 0, None, 0, 0, 0))
        )
    return jax.jit(
        jax.vmap(
            functools.partial(merge_step_sorted_patched, **kw),
            in_axes=(0, 0, 0, None, 0, None, 0, None, 0, 0),
        )
    )


def merge_step_sorted_patched_batch(
    states,
    text_ops,
    round_of,
    num_rounds,
    mark_ops,
    ranks,
    char_buf,
    multi,
    text_time,
    mark_time,
    maxk: int,
    has_marks: bool = True,
    wcache_in=None,
    mode: str = "delta",
    group_k: int | None = None,
    has_multi: bool = True,
    t_act: int | None = None,
    readback: str = "planes",
    span_cap: int = 8,
    cand_cap: int = 64,
):
    """Jitted batched entry point for the patch-emitting sorted merge.

    ``has_marks=False`` (static, from the encoded batch) compiles the
    mark-free fast path: no winner-cache init, no mark scan.
    ``wcache_in`` ([R, 2C, T, 4]) threads the persisted winner cache; when
    given, the marked path compiles WITHOUT the dominance init.
    ``mode`` selects the mark-row scan: "delta" (default — compact carry,
    full planes read/written once per launch) or "dense" (the full-plane
    carry variant, kept for A/B via PERITEXT_PATCH_PATH=dense).  Both emit
    byte-identical patch streams and states.  ``group_k``/``has_multi``/
    ``t_act`` are delta-only static specializations from the host's
    allowMultiple group census and mark-type registry (dense always
    compiles the full PATCH_GROUP_K / MAX_MARK_TYPES machinery); they are
    normalized here so dense mode keeps ONE jit cache entry.
    ``readback``/``span_cap`` select the record transfer format (see
    merge_step_sorted_patched): "compact" reads back [M, span_cap] run
    tables instead of the [M, 2C] mark planes.
    """
    if mode not in ("delta", "dense"):
        raise ValueError(f"unknown patched merge mode {mode!r}")
    if readback not in ("planes", "compact"):
        raise ValueError(f"unknown patch readback format {readback!r}")
    if mode == "dense" or not has_marks:
        group_k, has_multi, t_act = None, True, None
    if readback == "planes":
        span_cap = 8  # unused by the planes variant: keep ONE jit cache entry
    if readback == "planes" or not has_marks:
        cand_cap = 64  # unused by these variants: keep ONE jit cache entry
    fn = _merge_step_sorted_patched_batch(
        maxk, has_marks, wcache_in is not None, mode, group_k, has_multi, t_act,
        readback, span_cap, cand_cap,
    )
    args = [
        states, text_ops, round_of, jnp.int32(num_rounds), mark_ops, ranks,
        char_buf, multi, text_time, mark_time,
    ]
    if wcache_in is not None:
        args.append(wcache_in)
    return fn(*args)


def flatten_sources(state: DocState):
    """Per-element effective boundary bitset, for materialization.

    Tensorized getTextWithFormatting left-inheritance (peritext.ts:366-390):
    element i's marks change at its "before" slot if defined, else at the
    previous element's "after" slot; otherwise they carry from the left.
    Returns (mask [C, W], has_marks [C]): the resolved mark-op bitset per
    element (zeros/False where no boundary is in scope).
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    before_def = state.bnd_def[0::2]
    after_def = state.bnd_def[1::2]
    prev_after_def = jnp.roll(after_def, 1) & (ar > 0)
    d_slot = jnp.where(
        before_def, 2 * ar, jnp.where(prev_after_def, 2 * ar - 1, jnp.int32(-1))
    )
    has = (d_slot >= 0) & live
    src_elem = lax.cummax(jnp.where(has, ar, jnp.int32(-1)))
    src_slot = jnp.where(src_elem >= 0, d_slot[jnp.maximum(src_elem, 0)], jnp.int32(-1))
    mask = jnp.where(
        (src_slot >= 0)[:, None], state.bnd_mask[jnp.maximum(src_slot, 0)], jnp.uint32(0)
    )
    return mask, src_slot >= 0


flatten_sources_jit = jax.jit(flatten_sources)
flatten_sources_batch = jax.jit(jax.vmap(flatten_sources))


def cursor_elem(state: DocState, index: jax.Array):
    """Element id (ctr, act) of the index-th visible element.

    Tensorized getListElementId without the tombstone-peek option
    (reference micromerge.ts:762-805; cursors use the plain form,
    micromerge.ts:465-472).  Returns (ctr, act, found).
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    visible = (ar < state.length) & ~state.deleted
    rank = jnp.cumsum(visible.astype(jnp.int32)) - 1  # visible index per slot
    match = visible & (rank == index)
    i = jnp.argmax(match).astype(jnp.int32)
    return state.elem_ctr[i], state.elem_act[i], jnp.any(match)


def resolve_cursor_index(state: DocState, ctr: jax.Array, act: jax.Array):
    """Visible index of the element (ctr, act): count of visible elements
    before it (reference findListElement, micromerge.ts:731-755 — a deleted
    cursor target resolves to the position where it was)."""
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    match = live & (state.elem_ctr == ctr) & (state.elem_act == act)
    i = jnp.argmax(match).astype(jnp.int32)
    visible = live & ~state.deleted
    before = jnp.sum((ar < i) & visible).astype(jnp.int32)
    return before, jnp.any(match)


cursor_elem_jit = jax.jit(cursor_elem)
resolve_cursor_index_jit = jax.jit(resolve_cursor_index)
# Fleet variants: one launch resolves a cursor per replica.
cursor_elems_batch = jax.jit(jax.vmap(cursor_elem, in_axes=(0, 0)))
resolve_cursor_indices_batch = jax.jit(jax.vmap(resolve_cursor_index, in_axes=(0, 0, 0)))


def visible_elem_id(state: DocState, index: jax.Array, peek: jax.Array):
    """Element id of the index-th visible element, with the optional
    tombstone-peek rule for insert anchoring.

    Reference getListElementId (micromerge.ts:762-805): with ``peek``, look
    past the run of tombstones immediately following the target; if any of
    them carries a markOpsAfter boundary, anchor on the *last* such tombstone
    so new characters land after a non-growing span-end (motivating test:
    test/micromerge.ts:520-566).  Also reproduces the reference's falsy-zero
    quirk (micromerge.ts:794) — harmless here because the peek run starts
    strictly after a visible element.
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = ar < state.length
    visible = live & ~state.deleted
    rank = jnp.cumsum(visible.astype(jnp.int32)) - 1
    match = visible & (rank == index)
    i0 = jnp.argmax(match).astype(jnp.int32)
    found = jnp.any(match)

    first_vis_after = jnp.min(
        jnp.where(visible & (ar > i0), ar, jnp.int32(c))
    ).astype(jnp.int32)
    after_def = state.bnd_def[1::2]
    cand = live & state.deleted & (ar > i0) & (ar < first_vis_after) & after_def
    j_peek = jnp.max(jnp.where(cand, ar, jnp.int32(-1)))
    i = jnp.where(peek & (j_peek > 0), j_peek, i0)
    return state.elem_ctr[i], state.elem_act[i], found


visible_elem_id_jit = jax.jit(visible_elem_id)
visible_elem_ids_batch = jax.jit(jax.vmap(visible_elem_id, in_axes=(None, 0, None)))


def visible_length(state: DocState) -> jax.Array:
    ar = jnp.arange(state.capacity, dtype=jnp.int32)
    return jnp.sum((ar < state.length) & ~state.deleted).astype(jnp.int32)


visible_length_jit = jax.jit(visible_length)


def expand_mask_bits(mask: jax.Array, max_mark_ops: int) -> jax.Array:
    """[*, W] uint32 bitset rows -> [*, M] bool membership matrix."""
    m_idx = jnp.arange(max_mark_ops, dtype=jnp.int32)
    words = mask[..., m_idx // MASK_WORD_BITS]
    return ((words >> (m_idx % MASK_WORD_BITS).astype(jnp.uint32)) & 1).astype(bool)


def resolve_winners(state: DocState, present: jax.Array, ranks: jax.Array, multi: jax.Array) -> jax.Array:
    """LWW/multiset resolution of mark-op sets (reference opsToMarks,
    peritext.ts:294-326), as a dominance matmul.

    ``present[e, m]`` says mark op m is in element e's effective boundary set.
    Op m is *dominated* by m' when both address the same resolution group —
    same mark type for LWW marks, same (type, attr) for allowMultiple marks
    (comments resolve per comment id) — and m' has the greater op id.  The
    winners at an element are the present ops with no present dominator:
    a [C, M] x [M, M] masked matmul, which XLA maps onto the MXU.

    Returns winners [C, M] bool.  Effective marks follow directly: a winner
    with action addMark activates (type, attrs); a removeMark winner means
    the mark is absent.
    """
    is_multi = multi[state.mark_type]
    same_type = state.mark_type[:, None] == state.mark_type[None, :]
    same_attr = state.mark_attr[:, None] == state.mark_attr[None, :]
    same_group = same_type & (~is_multi[:, None] | same_attr)
    rank = ranks[state.mark_act]
    key_gt = (state.mark_ctr[None, :] > state.mark_ctr[:, None]) | (
        (state.mark_ctr[None, :] == state.mark_ctr[:, None])
        & (rank[None, :] > rank[:, None])
    )
    m_live = jnp.arange(state.max_mark_ops, dtype=jnp.int32) < state.mark_count
    dom = same_group & key_gt & m_live[None, :]  # dom[m, m']: m' dominates m
    dom_count = jnp.einsum(
        "em,nm->en", present.astype(jnp.float32), dom.astype(jnp.float32)
    )
    return present & (dom_count < 0.5) & m_live[None, :]


def convergence_digest(state: DocState, ranks: jax.Array, multi: jax.Array) -> jax.Array:
    """Order-sensitive checksum of the visible document + resolved marks.

    The TPU-native analog of the fuzzer's cross-replica convergence asserts
    (fuzz.ts:277-278): replicas that converged have equal digests, so a batch
    of replica pairs is convergence-checked with one vectorized compare (and
    across shards with a collective reduce).  Hashes *resolved* mark content
    (type/action/attr of winner ops), never table indices or bitset layout,
    because convergent replicas may hold the same ops at different table
    slots.
    """
    c = state.capacity
    ar = jnp.arange(c, dtype=jnp.int32)
    live = (ar < state.length) & ~state.deleted
    liveu = live.astype(jnp.uint32)
    vis_rank = (jnp.cumsum(liveu) - liveu) * liveu  # 0-based visible index
    mask, _ = flatten_sources(state)
    present = expand_mask_bits(mask, state.max_mark_ops)
    winners = resolve_winners(state, present, ranks, multi)
    adds = winners & (state.mark_action[None, :] == 0)
    mark_value = (
        state.mark_type.astype(jnp.uint32) * jnp.uint32(1000003)
        + (state.mark_attr + 1).astype(jnp.uint32) * jnp.uint32(8191)
        + jnp.uint32(17)
    )
    char_mix = jnp.sum((state.chars.astype(jnp.uint32) * jnp.uint32(2654435761) + vis_rank) * liveu)
    mark_mix = jnp.sum(
        adds.astype(jnp.uint32) * mark_value[None, :] * (vis_rank[:, None] * jnp.uint32(31) + 7) * liveu[:, None]
    )
    return jnp.uint32(2166136261) ^ char_mix ^ (mark_mix * jnp.uint32(31))


convergence_digest_batch = jax.jit(jax.vmap(convergence_digest, in_axes=(0, None, None)))


# ---------------------------------------------------------------------------
# Frontier-bounded window merge: device compute proportional to the edit
# ---------------------------------------------------------------------------
#
# Equivalence argument (mirroring the sorted-merge proof above).  Let W =
# [lo, hi] be a contiguous element range of the committed document such that
# for every op in the batch:
#
#   (i)   every insert's reference element and its ENTIRE skip run — the
#         contiguous post-reference run of elements whose op ids exceed the
#         *smallest* batch insert id (micromerge.ts:630-635) — lie in W, so
#         every placement position t satisfies lo <= t <= hi+1, and t = hi+1
#         only when hi is the last element of the document;
#   (ii)  every delete's target lies in W;
#   (iii) every mark op's start/end anchor slots, every DEFINED boundary
#         slot inside its [start, end) walk range, and the nearest defined
#         slot at or left of each anchor (the walk's carried currentOps
#         source, peritext.ts:181-186) lie in W's slot range;
#   (iv)  every insert's inherited-marks source (the nearest slot defined
#         at the insert's instant strictly left of its gap,
#         peritext.ts:328-330) lies in W's slot range.
#
# Then the merge restricted to the gathered window state — W's element and
# boundary rows with length = |W|, plus the full (small) mark table — equals
# the full-table merge restricted to W, and slots outside W are untouched by
# the full-table merge: placement reads only (i)'s rows (the skip-run stop
# rule never looks past the first non-skippable element, and window-local
# positions are global positions minus lo); tombstoning writes only (ii)'s
# rows; the mark walk reads/writes only (iii)'s slots, because in-range
# writes require definedness and anchor writes copy (iii)'s carry rows; and
# insert-row inheritance reads only (iv).  Scattering the merged window back
# over [lo, hi] with the tail shifted by the insert count therefore
# reproduces the full-table result exactly — states, patch records (with
# the vis_base/vis_after re-anchoring), and winner-cache rows alike.
# Visible indices decompose as global = local + vis_base because W is
# contiguous and all visibility changes happen inside W.
#
# The window conditions are computed HOST-side from the universe's causal
# mirror (ops/window.py); _window_ok re-verifies the membership conditions
# on device against the gathered window and the batch itself, so a stale or
# buggy census degrades to a full-table relaunch instead of corruption.


def _gather_window(state: DocState, start, hull_len, w_cap: int) -> DocState:
    """Slice a contiguous element window [start, start + w_cap) out of a
    replica state as a self-contained DocState of capacity ``w_cap``.

    ``length`` = ``hull_len`` (the census hull), so gathered slots beyond
    the hull — present only because w_cap is pow2-bucketed — read as dead
    padding to every kernel.  The mark table is small and rides whole.
    ``start`` must satisfy ``start + w_cap <= capacity`` (the host census
    clamps; dynamic_slice would silently re-anchor otherwise)."""
    s = jnp.int32(start)

    def win(p):
        return lax.dynamic_slice_in_dim(p, s, w_cap)

    return DocState(
        elem_ctr=win(state.elem_ctr),
        elem_act=win(state.elem_act),
        deleted=win(state.deleted),
        chars=win(state.chars),
        bnd_def=lax.dynamic_slice_in_dim(state.bnd_def, 2 * s, 2 * w_cap),
        bnd_mask=lax.dynamic_slice_in_dim(state.bnd_mask, 2 * s, 2 * w_cap, axis=0),
        mark_ctr=state.mark_ctr,
        mark_act=state.mark_act,
        mark_action=state.mark_action,
        mark_type=state.mark_type,
        mark_attr=state.mark_attr,
        length=jnp.int32(hull_len),
        mark_count=state.mark_count,
    )


def _scatter_window(state: DocState, win: DocState, start, hull_len) -> DocState:
    """Splice a merged window back into the full-capacity state.

    Elements [0, start) keep their rows, [start, start + win.length) come
    from the window, and the pre-batch tail shifts right by the insert
    count (win.length - hull_len).  Slots at or beyond the new length are
    masked to the dead-slot fills — the same convention the sort splice
    leaves behind — so a windowed and a full-table merge of the same batch
    produce byte-identical planes."""
    c = state.capacity
    w_cap = win.capacity
    start = jnp.int32(start)
    shift = win.length - jnp.int32(hull_len)
    new_n = state.length + shift
    ar = jnp.arange(c, dtype=jnp.int32)
    in_win = (ar >= start) & (ar < start + win.length)
    win_idx = jnp.clip(ar - start, 0, w_cap - 1)
    old_idx = jnp.clip(jnp.where(ar < start, ar, ar - shift), 0, c - 1)

    def mix(old, winp, fill):
        v = jnp.where(in_win, winp[win_idx], old[old_idx])
        return jnp.where(ar < new_n, v, fill)

    ar2 = jnp.arange(2 * c, dtype=jnp.int32)
    in_win2 = (ar2 >= 2 * start) & (ar2 < 2 * start + 2 * win.length)
    win_idx2 = jnp.clip(ar2 - 2 * start, 0, 2 * w_cap - 1)
    old_idx2 = jnp.clip(jnp.where(ar2 < 2 * start, ar2, ar2 - 2 * shift), 0, 2 * c - 1)
    live2 = ar2 < 2 * new_n
    bnd_def = jnp.where(
        live2, jnp.where(in_win2, win.bnd_def[win_idx2], state.bnd_def[old_idx2]), False
    )
    bnd_mask = jnp.where(
        live2[:, None],
        jnp.where(
            in_win2[:, None], win.bnd_mask[win_idx2], state.bnd_mask[old_idx2]
        ),
        jnp.uint32(0),
    )
    return DocState(
        elem_ctr=mix(state.elem_ctr, win.elem_ctr, 0),
        elem_act=mix(state.elem_act, win.elem_act, 0),
        deleted=mix(state.deleted, win.deleted, False),
        chars=mix(state.chars, win.chars, 0),
        bnd_def=bnd_def,
        bnd_mask=bnd_mask,
        mark_ctr=win.mark_ctr,
        mark_act=win.mark_act,
        mark_action=win.mark_action,
        mark_type=win.mark_type,
        mark_attr=win.mark_attr,
        length=new_n,
        mark_count=win.mark_count,
    )


def _window_ok(win0: DocState, text_ops, mark_ops, w_cap: int):
    """Device-side soundness check of the host window census.

    Verifies, against the PRE-merge gathered window, the membership half of
    the window conditions: every text op's reference (HEAD, a window
    element, or a batch-created element), every mark anchor likewise, and
    that the window has room for the batch's inserts.  A False verdict
    makes the universe discard the windowed result and relaunch the
    full-table path — the adaptive always-correct fallback.  (The skip-run
    bound (i) is not re-checkable from the window alone; it holds because
    the census computes it from the mirror, which is itself a readback of
    committed device state.)"""
    ln = win0.length
    live = jnp.arange(w_cap, dtype=jnp.int32) < ln
    kind = text_ops[:, K_KIND]
    is_ins = (kind == KIND_INSERT) | (kind == KIND_INSERT_RUN)
    is_del = kind == KIND_DELETE
    k = jnp.where(kind == KIND_INSERT_RUN, text_ops[:, K_RUN_LEN], 1) * is_ins.astype(
        jnp.int32
    )

    def found_in_win(qc, qa):
        return jnp.any(
            live[None, :]
            & (win0.elem_ctr[None, :] == qc[:, None])
            & (win0.elem_act[None, :] == qa[:, None]),
            axis=1,
        )

    def found_in_batch(qc, qa):
        return jnp.any(
            is_ins[None, :]
            & (qa[:, None] == text_ops[None, :, K_ACT])
            & (qc[:, None] >= text_ops[None, :, K_CTR])
            & (qc[:, None] < text_ops[None, :, K_CTR] + k[None, :]),
            axis=1,
        )

    ref_ctr = text_ops[:, K_REF_CTR]
    ref_act = text_ops[:, K_REF_ACT]
    is_head = (ref_ctr == 0) & (ref_act == 0)
    ref_ok = found_in_win(ref_ctr, ref_act) | found_in_batch(ref_ctr, ref_act)
    text_ok = jnp.all(~(is_ins | is_del) | jnp.where(is_ins, is_head | ref_ok, ref_ok))

    mvalid = mark_ops[:, K_KIND] == KIND_MARK
    s_ok = found_in_win(mark_ops[:, K_SCTR], mark_ops[:, K_SACT]) | found_in_batch(
        mark_ops[:, K_SCTR], mark_ops[:, K_SACT]
    )
    e_ok = (
        (mark_ops[:, K_EKIND] == 2)
        | found_in_win(mark_ops[:, K_ECTR], mark_ops[:, K_EACT])
        | found_in_batch(mark_ops[:, K_ECTR], mark_ops[:, K_EACT])
    )
    mark_ok = jnp.all(~mvalid | (s_ok & e_ok))
    fit_ok = ln + jnp.sum(k) <= w_cap
    return text_ok & mark_ok & fit_ok


def merge_step_sorted_windowed(
    state: DocState,
    start,
    hull_len,
    text_ops,
    round_of,
    num_rounds,
    mark_ops,
    ranks,
    char_buf,
    maxk: int,
    w_cap: int,
):
    """merge_step_sorted over a gathered window, scattered back.

    Returns ``(new_state, wrec)`` where wrec carries the device census
    verdict (``wok``) and the post-merge window planes (``w_ctr``/``w_act``/
    ``w_del``/``w_def``) the universe splices into its host mirror — so the
    mirror stays a readback of device truth with O(window) transfer.  On
    ``wok=False`` the returned state is meaningless and must be discarded
    (the caller relaunches the full-table path)."""
    win0 = _gather_window(state, start, hull_len, w_cap)
    wok = _window_ok(win0, text_ops, mark_ops, w_cap)
    new_win = merge_step_sorted(
        win0, text_ops, round_of, num_rounds, mark_ops, ranks, char_buf, maxk
    )
    new_state = _scatter_window(state, new_win, start, hull_len)
    wrec = {
        "wok": wok,
        "w_ctr": new_win.elem_ctr,
        "w_act": new_win.elem_act,
        "w_del": new_win.deleted,
        "w_def": new_win.bnd_def,
    }
    return new_state, wrec


@functools.lru_cache(maxsize=None)
def _merge_step_sorted_windowed_batch(maxk: int, w_cap: int):
    return jax.jit(
        jax.vmap(
            functools.partial(merge_step_sorted_windowed, maxk=maxk, w_cap=w_cap),
            in_axes=(0, 0, 0, 0, 0, None, 0, None, 0),
        )
    )


def merge_step_sorted_windowed_batch(
    states, starts, hull_lens, text_ops, round_of, num_rounds, mark_ops, ranks,
    char_buf, maxk: int, w_cap: int,
):
    fn = _merge_step_sorted_windowed_batch(maxk, w_cap)
    return fn(
        states, starts, hull_lens, text_ops, round_of, jnp.int32(num_rounds),
        mark_ops, ranks, char_buf,
    )


def _gather_wcache_window(wcache, start, w_cap: int):
    return lax.dynamic_slice_in_dim(wcache, 2 * jnp.int32(start), 2 * w_cap, axis=0)


def _scatter_wcache_window(wcache, win_rows, start, hull_len, win_len, old_len):
    """Boundary-slot scatter of updated window winner-cache rows back into
    the full [2C, T, 4] cache (same shift rule as _scatter_window; rows at
    or beyond the new length mask to the empty entry, matching what a
    fresh dominance init over zeroed rows produces)."""
    two_c = wcache.shape[0]
    w2 = win_rows.shape[0]
    start = jnp.int32(start)
    shift = jnp.int32(win_len) - jnp.int32(hull_len)
    new_n2 = 2 * (jnp.int32(old_len) + shift)
    ar2 = jnp.arange(two_c, dtype=jnp.int32)
    in_win = (ar2 >= 2 * start) & (ar2 < 2 * start + 2 * jnp.int32(win_len))
    win_idx = jnp.clip(ar2 - 2 * start, 0, w2 - 1)
    old_idx = jnp.clip(jnp.where(ar2 < 2 * start, ar2, ar2 - 2 * shift), 0, two_c - 1)
    empty = jnp.array([-1, -1, 0, 0], jnp.int32)
    v = jnp.where(in_win[:, None, None], win_rows[win_idx], wcache[old_idx])
    return jnp.where((ar2 < new_n2)[:, None, None], v, empty[None, None, :])


def merge_step_sorted_patched_windowed(
    state: DocState,
    start,
    hull_len,
    vis_base,
    vis_after,
    text_ops,
    round_of,
    num_rounds,
    mark_ops,
    ranks,
    char_buf,
    multi,
    text_time,
    mark_time,
    maxk: int,
    has_marks: bool = True,
    wcache_in: jax.Array | None = None,
    mode: str = "delta",
    group_k: int | None = None,
    has_multi: bool = True,
    t_act: int | None = None,
    readback: str = "planes",
    span_cap: int = 8,
    cand_cap: int = 64,
    w_cap: int = 256,
):
    """merge_step_sorted_patched over a gathered window, scattered back.

    Records come out on GLOBAL visible coordinates (the vis_base/vis_after
    re-anchoring runs before span compaction), so the host assemblers are
    oblivious to windowing; ``wcache_in`` here is the FULL persisted cache
    — its window rows ride the window merge and scatter back, so cache
    warmth survives windowed ingests.  wrec extras as in
    merge_step_sorted_windowed."""
    win0 = _gather_window(state, start, hull_len, w_cap)
    wok = _window_ok(win0, text_ops, mark_ops, w_cap)
    wc_win = (
        None if wcache_in is None else _gather_wcache_window(wcache_in, start, w_cap)
    )
    new_win, rec = merge_step_sorted_patched(
        win0,
        text_ops,
        round_of,
        num_rounds,
        mark_ops,
        ranks,
        char_buf,
        multi,
        text_time,
        mark_time,
        maxk,
        has_marks=has_marks,
        wcache_in=wc_win,
        mode=mode,
        group_k=group_k,
        has_multi=has_multi,
        t_act=t_act,
        readback=readback,
        span_cap=span_cap,
        cand_cap=cand_cap,
        vis_base=vis_base,
        vis_after=vis_after,
    )
    new_state = _scatter_window(state, new_win, start, hull_len)
    wc = rec.pop("wcache", None)
    if wcache_in is not None and wc is not None:
        rec["wcache"] = _scatter_wcache_window(
            wcache_in, wc, start, hull_len, new_win.length, state.length
        )
    rec["wok"] = wok
    rec["w_ctr"] = new_win.elem_ctr
    rec["w_act"] = new_win.elem_act
    rec["w_del"] = new_win.deleted
    rec["w_def"] = new_win.bnd_def
    return new_state, rec


@functools.lru_cache(maxsize=None)
def _merge_step_sorted_patched_windowed_batch(
    maxk: int,
    has_marks: bool,
    has_wcache: bool,
    mode: str,
    group_k: int | None,
    has_multi: bool,
    t_act: int | None,
    readback: str,
    span_cap: int,
    cand_cap: int,
    w_cap: int,
):
    kw = dict(
        maxk=maxk, has_marks=has_marks, mode=mode, group_k=group_k,
        has_multi=has_multi, t_act=t_act, readback=readback, span_cap=span_cap,
        cand_cap=cand_cap, w_cap=w_cap,
    )
    if has_wcache:

        def call(st, s, h, vb, va, t, ro, nr, m, rk, b, mu, tt, mt, wc):
            return merge_step_sorted_patched_windowed(
                st, s, h, vb, va, t, ro, nr, m, rk, b, mu, tt, mt,
                wcache_in=wc, **kw
            )

        return jax.jit(
            jax.vmap(
                call,
                in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0, None, 0, None, 0, 0, 0),
            )
        )
    return jax.jit(
        jax.vmap(
            functools.partial(merge_step_sorted_patched_windowed, **kw),
            in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0, None, 0, None, 0, 0),
        )
    )


def merge_step_sorted_patched_windowed_batch(
    states,
    starts,
    hull_lens,
    vis_base,
    vis_after,
    text_ops,
    round_of,
    num_rounds,
    mark_ops,
    ranks,
    char_buf,
    multi,
    text_time,
    mark_time,
    maxk: int,
    w_cap: int,
    has_marks: bool = True,
    wcache_in=None,
    mode: str = "delta",
    group_k: int | None = None,
    has_multi: bool = True,
    t_act: int | None = None,
    readback: str = "planes",
    span_cap: int = 8,
    cand_cap: int = 64,
):
    """Jitted batched entry for the windowed patch-emitting sorted merge
    (same static-arg normalization as merge_step_sorted_patched_batch)."""
    if mode not in ("delta", "dense"):
        raise ValueError(f"unknown patched merge mode {mode!r}")
    if readback not in ("planes", "compact"):
        raise ValueError(f"unknown patch readback format {readback!r}")
    if mode == "dense" or not has_marks:
        group_k, has_multi, t_act = None, True, None
    if readback == "planes":
        span_cap = 8
    if readback == "planes" or not has_marks:
        cand_cap = 64
    fn = _merge_step_sorted_patched_windowed_batch(
        maxk, has_marks, wcache_in is not None, mode, group_k, has_multi, t_act,
        readback, span_cap, cand_cap, w_cap,
    )
    args = [
        states, starts, hull_lens, vis_base, vis_after, text_ops, round_of,
        jnp.int32(num_rounds), mark_ops, ranks, char_buf, multi, text_time,
        mark_time,
    ]
    if wcache_in is not None:
        args.append(wcache_in)
    return fn(*args)
