"""Host-side encoding: wire-format changes -> dense op tensors.

The ChangeQueue analog at the host<->device boundary (SURVEY.md §2.4): a
causally-sorted batch of changes is flattened into fixed-width int32 op rows
(one row per *internal* op, kernels.py field layout), padded to a bucketed
length so jit caches stay warm, and uploaded once per apply call.

Actor strings and mark attrs are interned to dense ids here; the device only
ever sees integers.  Map-object ops (makeList/makeMap/set/del on maps —
structural control-plane ops, micromerge.ts:578-602) are split out for host
handling: the device engine's data plane is the text list.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from peritext_tpu.ids import ActorRegistry, parse_op_id
from peritext_tpu.ops import kernels as K
from peritext_tpu.schema import MARK_TYPE_ID


class AttrRegistry:
    """Interns mark attr dicts to dense ids (canonical-JSON keyed)."""

    def __init__(self) -> None:
        self._id_of: Dict[str, int] = {}
        self._attrs: List[Dict[str, Any]] = []

    def intern(self, attrs: Optional[Dict[str, Any]]) -> int:
        if not attrs:
            return -1
        key = json.dumps(attrs, sort_keys=True)
        i = self._id_of.get(key)
        if i is None:
            i = len(self._attrs)
            self._id_of[key] = i
            self._attrs.append(dict(attrs))
        return i

    def decode(self, i: int) -> Optional[Dict[str, Any]]:
        if i < 0:
            return None
        return dict(self._attrs[i])

    @property
    def values(self) -> List[Dict[str, Any]]:
        return [dict(a) for a in self._attrs]


_BOUNDARY_KIND = {"before": 0, "after": 1, "endOfText": 2}

# Delivery-instant pad for patch-path timeline arrays: beyond any real
# stream position (kernels._TIME_BIG), so padded rows never count as
# "applied before" anything.
TIME_PAD = 1 << 30


def encode_internal_op(
    op: Dict[str, Any], actors: ActorRegistry, attrs: AttrRegistry
) -> Optional[np.ndarray]:
    """One wire-format internal op -> an int32 op row, or None for map ops."""
    row = np.zeros(K.OP_FIELDS, np.int32)
    ctr, actor = parse_op_id(op["opId"])
    row[K.K_CTR] = ctr
    row[K.K_ACT] = actors.intern(actor)
    action = op["action"]

    if action == "set" and op.get("insert"):
        row[K.K_KIND] = K.KIND_INSERT
        elem = op.get("elemId")
        if elem is not None:
            ref_ctr, ref_actor = parse_op_id(elem)
            row[K.K_REF_CTR] = ref_ctr
            row[K.K_REF_ACT] = actors.intern(ref_actor)
        value = op["value"]
        if not isinstance(value, str) or len(value) != 1:
            raise ValueError(f"Expected 1-char string insert value, got {value!r}")
        row[K.K_PAYLOAD] = ord(value)
        return row

    if action == "del" and op.get("elemId") is not None:
        row[K.K_KIND] = K.KIND_DELETE
        ref_ctr, ref_actor = parse_op_id(op["elemId"])
        row[K.K_REF_CTR] = ref_ctr
        row[K.K_REF_ACT] = actors.intern(ref_actor)
        return row

    if action in ("addMark", "removeMark"):
        row[K.K_KIND] = K.KIND_MARK
        row[K.K_MACTION] = 0 if action == "addMark" else 1
        row[K.K_MTYPE] = MARK_TYPE_ID[op["markType"]]
        row[K.K_MATTR] = attrs.intern(op.get("attrs"))
        start, end = op["start"], op["end"]
        if start["type"] not in ("before", "after"):
            # startGrows is hardcoded false upstream (peritext.ts:466), so
            # startOfText anchors cannot be produced by any writer.
            raise NotImplementedError(f"start anchor {start['type']!r}")
        row[K.K_SKIND] = _BOUNDARY_KIND[start["type"]]
        sctr, sact = parse_op_id(start["elemId"])
        row[K.K_SCTR] = sctr
        row[K.K_SACT] = actors.intern(sact)
        row[K.K_EKIND] = _BOUNDARY_KIND[end["type"]]
        if end["type"] != "endOfText":
            ectr, eact = parse_op_id(end["elemId"])
            row[K.K_ECTR] = ectr
            row[K.K_EACT] = actors.intern(eact)
        return row

    # Map-object / structural op: host concern.
    return None


_BOUNDARY_NAME = {v: k for k, v in _BOUNDARY_KIND.items()}


def decode_internal_op(
    row: np.ndarray,
    actors: ActorRegistry,
    attrs: AttrRegistry,
    obj: Optional[str],
) -> Dict[str, Any]:
    """Inverse of encode_internal_op: an op row back to the wire format.

    ``obj`` is the containing list's object id (op rows don't carry it; the
    log envelope does).  Round-trip fidelity is tested in
    tests/test_native_codec.py.
    """
    from peritext_tpu import schema
    from peritext_tpu.ids import make_op_id

    op_id = make_op_id(int(row[K.K_CTR]), actors.actor(int(row[K.K_ACT])))
    kind = int(row[K.K_KIND])
    if kind == K.KIND_INSERT:
        op: Dict[str, Any] = {
            "opId": op_id,
            "action": "set",
            "obj": obj,
            "insert": True,
            "value": chr(int(row[K.K_PAYLOAD])),
        }
        if int(row[K.K_REF_CTR]) != 0 or int(row[K.K_REF_ACT]) != 0:
            op["elemId"] = make_op_id(
                int(row[K.K_REF_CTR]), actors.actor(int(row[K.K_REF_ACT]))
            )
        # Match the reference's key order: elemId precedes insert/value in
        # serialized traces; key order is irrelevant to dict equality.
        return op
    if kind == K.KIND_DELETE:
        return {
            "opId": op_id,
            "action": "del",
            "obj": obj,
            "elemId": make_op_id(
                int(row[K.K_REF_CTR]), actors.actor(int(row[K.K_REF_ACT]))
            ),
        }
    if kind == K.KIND_MARK:
        op = {
            "opId": op_id,
            "action": "addMark" if int(row[K.K_MACTION]) == 0 else "removeMark",
            "obj": obj,
            "start": {
                "type": _BOUNDARY_NAME[int(row[K.K_SKIND])],
                "elemId": make_op_id(
                    int(row[K.K_SCTR]), actors.actor(int(row[K.K_SACT]))
                ),
            },
            "markType": schema.ALL_MARKS[int(row[K.K_MTYPE])],
        }
        if int(row[K.K_EKIND]) == 2:
            op["end"] = {"type": "endOfText"}
        else:
            op["end"] = {
                "type": _BOUNDARY_NAME[int(row[K.K_EKIND])],
                "elemId": make_op_id(
                    int(row[K.K_ECTR]), actors.actor(int(row[K.K_EACT]))
                ),
            }
        attr = attrs.decode(int(row[K.K_MATTR]))
        if attr is not None:
            op["attrs"] = attr
        return op
    raise ValueError(f"cannot decode op row of kind {kind}")


def encode_changes(
    changes: Sequence[Dict[str, Any]],
    actors: ActorRegistry,
    attrs: AttrRegistry,
    text_obj: Optional[str] = None,
) -> Tuple[np.ndarray, List[Dict[str, Any]], Dict[str, int]]:
    """Flatten a causally-ordered change batch into device op rows.

    Returns (rows [N, OP_FIELDS], host_ops, counts) where host_ops is a list
    of ``(pos, op)`` pairs — structural/nested-object ops routed to the host
    object store, tagged with their flat position in the batch's op stream so
    the patch path can interleave host and device patches in true op order —
    and counts tallies device inserts and mark ops for capacity pre-checks
    (plus ``row_pos``, the flat positions of the device rows, and
    ``text_obj``, the device text-list binding after this batch).

    Ops route by target object, mirroring the reference's per-object dispatch
    (micromerge.ts:534-608): ops on the device text list become op rows;
    everything else — map ops, nested lists, second lists — goes host-side.
    The first root ``makeList`` with key "text" establishes the device
    binding; an op targeting an object the host store doesn't know raises
    there rather than being silently spliced into the text document.

    ``text_obj`` is the replica's established device text-list id (None
    before genesis).
    """
    rows: List[np.ndarray] = []
    row_pos: List[int] = []
    host_ops: List[Tuple[int, Dict[str, Any]]] = []
    counts: Dict[str, Any] = {"insert": 0, "mark": 0}
    pos = 0
    for change in changes:
        for op in change["ops"]:
            obj = op.get("obj")
            if obj != text_obj or text_obj is None:
                # Structural op (map makeList/makeMap/set/del), or a list op
                # on a host-side (non-device) list: the host store applies
                # it.  Route before encoding — host lists may hold values the
                # device char plane can't (and must not) encode.
                # The device binding is the first makeList with key "text"
                # on the ROOT map only (absent obj == ROOT on the wire); a
                # "text"-keyed list inside a nested map stays host-side.
                if (
                    op["action"] == "makeList"
                    and op.get("obj") is None
                    and op.get("key") == "text"
                    and text_obj is None
                ):
                    text_obj = op["opId"]
                host_ops.append((pos, op))
            else:
                row = encode_internal_op(op, actors, attrs)
                if row is None:
                    raise ValueError(
                        f"op {op.get('opId')!r} is a map op targeting the "
                        f"device text list {text_obj!r}"
                    )
                if row[K.K_KIND] == K.KIND_INSERT:
                    counts["insert"] += 1
                elif row[K.K_KIND] == K.KIND_MARK:
                    counts["mark"] += 1
                rows.append(row)
                row_pos.append(pos)
            pos += 1
    if rows:
        out = np.stack(rows)
    else:
        out = np.zeros((0, K.OP_FIELDS), np.int32)
    counts["row_pos"] = np.asarray(row_pos, np.int64)
    counts["text_obj"] = text_obj
    return out, host_ops, counts


def split_rows(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Split encoded op rows into (text ops, mark ops), each in causal order.

    Feeds the two-phase fast merge path (kernels.merge_step); see the
    state-equivalence argument there for why the split preserves semantics.
    """
    kinds = rows[:, K.K_KIND]
    is_mark = kinds == K.KIND_MARK
    return rows[~is_mark], rows[is_mark]


def fuse_insert_runs(
    rows: np.ndarray,
    max_run: Optional[int] = None,
    pos: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Fuse chained insert rows into KIND_INSERT_RUN rows + a char buffer.

    A chain is consecutive rows where each insert references the previous
    row's op id with consecutive counters from the same actor — exactly what
    one insert input op expands to (micromerge.ts:351-361).  Chains apply as
    one scan step each (see kernels._apply_text_op's contiguity argument).
    Returns (fused rows, char buffer padded for in-bounds dynamic slices,
    fused positions or None).

    ``max_run`` caps chain length; the default (kernels.MAX_RUN_LEN) is what
    the scan/Pallas paths' static char windows require.  The sort-based
    placement path scatters runs with no window, so it fuses unbounded
    (pass ``max_run=0``) — a whole pasted document is one row.

    ``pos`` (the rows' flat batch-stream positions, counts["row_pos"]) gates
    fusion on *delivery adjacency* and returns each fused row's first-op
    position: the patch-emitting sorted path models a run as k consecutive
    timeline instants, so two chained inserts separated in the delivery
    stream (by a mark or host op) must stay unfused — an op between the
    chars could change what the later chars' insert patches inherit.  The
    patch-free path passes no ``pos`` (state equivalence doesn't care, per
    the two-phase argument).
    """
    if max_run is None:
        max_run = K.MAX_RUN_LEN
    if max_run <= 0:
        max_run = 1 << 30
    fused: List[np.ndarray] = []
    fused_pos: List[int] = []
    chars: List[int] = []
    i = 0
    n = rows.shape[0]
    while i < n:
        row = rows[i]
        if pos is not None:
            fused_pos.append(int(pos[i]))
        if row[K.K_KIND] != K.KIND_INSERT:
            fused.append(row)
            i += 1
            continue
        j = i + 1
        while (
            j < n
            and j - i < max_run
            and rows[j][K.K_KIND] == K.KIND_INSERT
            and rows[j][K.K_ACT] == rows[j - 1][K.K_ACT]
            and rows[j][K.K_CTR] == rows[j - 1][K.K_CTR] + 1
            and rows[j][K.K_REF_CTR] == rows[j - 1][K.K_CTR]
            and rows[j][K.K_REF_ACT] == rows[j - 1][K.K_ACT]
            and (pos is None or pos[j] == pos[j - 1] + 1)
        ):
            j += 1
        if j - i == 1:
            fused.append(row)
        else:
            run = np.zeros(K.OP_FIELDS, np.int32)
            run[K.K_KIND] = K.KIND_INSERT_RUN
            run[K.K_CTR] = row[K.K_CTR]
            run[K.K_ACT] = row[K.K_ACT]
            run[K.K_REF_CTR] = row[K.K_REF_CTR]
            run[K.K_REF_ACT] = row[K.K_REF_ACT]
            run[K.K_PAYLOAD] = len(chars)
            run[K.K_RUN_LEN] = j - i
            chars.extend(int(rows[p][K.K_PAYLOAD]) for p in range(i, j))
            fused.append(run)
        i = j
    out_rows = np.stack(fused) if fused else np.zeros((0, K.OP_FIELDS), np.int32)
    buf = np.zeros(len(chars) + K.MAX_RUN_LEN, np.int32)
    buf[: len(chars)] = chars
    return out_rows, buf, (np.asarray(fused_pos, np.int64) if pos is not None else None)


def compute_rounds(rows: np.ndarray) -> Tuple[np.ndarray, int]:
    """Reference-depth labels for sort-based batch placement.

    An op whose reference element pre-exists the batch gets round 0; an op
    referencing an element *created by an earlier row of this batch* gets
    that row's round + 1 (it must wait until its reference is placed).
    Returns (round_of [N] int32, num_rounds).  Causal order guarantees a
    reference row always precedes its dependents.
    """
    n = rows.shape[0]
    round_of = np.zeros(n, np.int32)
    if n == 0:
        return round_of, 1
    created: Dict[Tuple[int, int], int] = {}
    kinds = rows[:, K.K_KIND]
    for i in range(n):
        kind = kinds[i]
        if kind == K.KIND_PAD:
            continue
        ref = (int(rows[i, K.K_REF_ACT]), int(rows[i, K.K_REF_CTR]))
        j = created.get(ref)
        if j is not None:
            round_of[i] = round_of[j] + 1
        if kind == K.KIND_INSERT:
            created[(int(rows[i, K.K_ACT]), int(rows[i, K.K_CTR]))] = i
        elif kind == K.KIND_INSERT_RUN:
            act = int(rows[i, K.K_ACT])
            first = int(rows[i, K.K_CTR])
            for ctr in range(first, first + int(rows[i, K.K_RUN_LEN])):
                created[(act, ctr)] = i
    return round_of, int(round_of.max()) + 1


def _fuse_and_rounds(
    text_rows_list: Sequence[np.ndarray],
    max_run: int,
    pos_list: Optional[Sequence[np.ndarray]] = None,
) -> Tuple[list, list, list, list, int, int]:
    fused, bufs, round_labels, fused_pos = [], [], [], []
    num_rounds, maxk = 1, 1
    for i, rows in enumerate(text_rows_list):
        fr, fb, fp = fuse_insert_runs(
            rows, max_run=max_run, pos=None if pos_list is None else pos_list[i]
        )
        ro, nr = compute_rounds(fr)
        num_rounds = max(num_rounds, nr)
        runs = fr[:, K.K_KIND] == K.KIND_INSERT_RUN
        if runs.any():
            maxk = max(maxk, int(fr[runs, K.K_RUN_LEN].max()))
        fused.append(fr)
        bufs.append(fb)
        round_labels.append(ro)
        fused_pos.append(fp)
    return fused, bufs, round_labels, fused_pos, num_rounds, maxk


def prepare_sorted_batch(
    text_rows_list: Sequence[np.ndarray],
    max_run: int = 0,
    fallback_max_rounds: Optional[int] = None,
    pos_list: Optional[Sequence[np.ndarray]] = None,
    restack_on_fallback: bool = True,
) -> Dict[str, Any]:
    """Shared preparation for the sort-based placement path.

    Fuses insert runs (unbounded by default — placement scatters need no
    static window), labels reference-depth rounds, and pads/stacks the
    per-stream row arrays.  Returns a dict with ``text`` [G, L, F],
    ``rounds`` [G, L], ``bufs`` [G, B], ``num_rounds``, ``maxk`` (bucketed
    run-length cap for the kernel's static block width), and ``fell_back``.
    Used by the universe ingest path, the benchmark, and the differential
    tests so the three can never diverge.

    With ``fallback_max_rounds``, batches whose reference depth exceeds it
    (deep single-writer histories, where placement rounds degenerate) are
    re-fused with the scan path's MAX_RUN_LEN window instead, before any
    padding/stacking happens, and flagged ``fell_back=True`` so the caller
    can launch the sequential scan kernel.

    With ``pos_list`` (per-stream row_pos arrays), run fusion is gated on
    delivery adjacency and the result carries ``text_pos`` [G, L] — each
    fused row's first-op stream instant, padded with TIME_PAD — for the
    patch-emitting sorted path's timeline reconstruction.

    With ``restack_on_fallback=False``, a fallback returns just
    ``{"fell_back": True}`` — for callers that route deep batches to a
    different kernel entirely and would discard the re-fused arrays.
    """
    fused, bufs, round_labels, fused_pos, num_rounds, maxk = _fuse_and_rounds(
        text_rows_list, max_run, pos_list
    )
    fell_back = False
    if fallback_max_rounds is not None and num_rounds > fallback_max_rounds:
        fell_back = True
        if not restack_on_fallback:
            # Caller routes fallbacks elsewhere (the interleaved patch
            # scan); don't pay the MAX_RUN_LEN re-fuse + pad/stack it
            # would discard.
            return {"fell_back": True}
        fused, bufs, round_labels, fused_pos, num_rounds, maxk = _fuse_and_rounds(
            text_rows_list, K.MAX_RUN_LEN, pos_list
        )
    text_pad = bucket_length(max(max(f.shape[0] for f in fused), 1))
    buf_pad = bucket_length(max(max(b.shape[0] for b in bufs), K.MAX_RUN_LEN))
    out = {
        "text": np.stack([pad_rows(f, text_pad) for f in fused]),
        "rounds": np.stack(
            [np.pad(ro, (0, text_pad - ro.shape[0])) for ro in round_labels]
        ).astype(np.int32),
        "bufs": np.stack([pad_buffer(b, buf_pad) for b in bufs]),
        "num_rounds": num_rounds,
        "maxk": bucket_length(maxk, minimum=1),
        "fell_back": fell_back,
    }
    if pos_list is not None:
        out["text_pos"] = np.stack(
            [
                np.pad(fp, (0, text_pad - fp.shape[0]), constant_values=TIME_PAD)
                for fp in fused_pos
            ]
        ).astype(np.int32)
    return out


def pad_buffer(buf: np.ndarray, length: int) -> np.ndarray:
    if buf.shape[0] > length:
        raise ValueError(f"char buffer of {buf.shape[0]} exceeds pad length {length}")
    out = np.zeros(length, np.int32)
    out[: buf.shape[0]] = buf
    return out


def pad_rows(rows: np.ndarray, length: int) -> np.ndarray:
    """Pad op rows with KIND_PAD to a fixed length."""
    if rows.shape[0] > length:
        raise ValueError(f"op batch of {rows.shape[0]} exceeds pad length {length}")
    out = np.zeros((length, K.OP_FIELDS), np.int32)
    out[: rows.shape[0]] = rows
    return out


def bucket_length(n: int, minimum: int = 8) -> int:
    """Round up to a power of two so jit compilation caches stay warm."""
    length = minimum
    while length < n:
        length *= 2
    return length
