"""Operation/actor identity primitives.

Reference: micromerge.ts:34-55 (ActorId / OperationId / Clock types) and
micromerge.ts:812-827 (compareOpIds).

On the wire an operation id is the string ``"{counter}@{actorId}"`` and the
total order is (counter, then *lexicographic* actor id) — a Lamport-style
order.  The TPU engine never touches strings: actors are interned to stable
integer ids by :class:`ActorRegistry`, and comparisons use the actor's
*lexicographic rank* (recomputed when new actors appear) so that the tuple
``(counter, rank)`` compares exactly like the reference's string compare.
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

_OPID_RE = re.compile(r"^([0-9]+)@(.*)$", re.DOTALL)


def parse_op_id(op_id: str) -> Tuple[int, str]:
    """Split ``"ctr@actor"`` into ``(ctr, actor)``. Reference micromerge.ts:815-823."""
    m = _OPID_RE.match(op_id)
    if m is None:
        raise ValueError(f"Invalid operation ID: {op_id}")
    return int(m.group(1)), m.group(2)


def make_op_id(counter: int, actor: str) -> str:
    return f"{counter}@{actor}"


def op_sort_key(op_id: str) -> Tuple[int, str]:
    """Sort key realizing the reference's total order on op ids."""
    return parse_op_id(op_id)


def compare_op_ids(id1: str, id2: str) -> int:
    """Total order on op ids: counter first, then lexicographic actor.

    Returns -1/0/+1.  Reference micromerge.ts:812-827.
    """
    if id1 == id2:
        return 0
    c1, a1 = parse_op_id(id1)
    c2, a2 = parse_op_id(id2)
    if c1 < c2 or (c1 == c2 and a1 < a2):
        return -1
    return 1


class ActorRegistry:
    """Interns actor-id strings to dense integer ids.

    The integer id is stable for the lifetime of the registry (safe to store
    in device tensors).  ``ranks()`` returns, for each interned id, the
    actor's rank in lexicographic string order — the key the TPU kernels use
    so that ``(counter, rank)`` tuple comparison reproduces the reference's
    ``compareOpIds`` (micromerge.ts:826: equal counters fall back to
    ``actor1 < actor2`` string comparison).
    """

    def __init__(self) -> None:
        self._id_of: Dict[str, int] = {}
        self._actors: List[str] = []
        self._ranks: List[int] | None = None

    def __len__(self) -> int:
        return len(self._actors)

    def intern(self, actor: str) -> int:
        i = self._id_of.get(actor)
        if i is None:
            i = len(self._actors)
            self._id_of[actor] = i
            self._actors.append(actor)
            self._ranks = None  # invalidate
        return i

    def actor(self, i: int) -> str:
        return self._actors[i]

    def id_of(self, actor: str) -> int:
        return self._id_of[actor]

    def __contains__(self, actor: str) -> bool:
        return actor in self._id_of

    def ranks(self) -> List[int]:
        """rank_of_id[i] = lexicographic rank of actor with intern id i."""
        if self._ranks is None:
            order = sorted(range(len(self._actors)), key=lambda i: self._actors[i])
            ranks = [0] * len(self._actors)
            for rank, i in enumerate(order):
                ranks[i] = rank
            self._ranks = ranks
        return self._ranks

    @property
    def actors(self) -> List[str]:
        return list(self._actors)
