"""Test fixtures shared by the framework's test suite and fuzzer.

Mirrors the reference's fixtures: generateDocs (test/generateDocs.ts:11-42)
and the concurrent-write harness shape (test/micromerge.ts:46-86).
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.oracle import Doc, accumulate_patches

DEFAULT_TEXT = "The Peritext editor"

# Every env knob that can force the patch path off the sorted merge.  An
# honest sorted-vs-scan A/B must clear ALL of these for its sorted leg;
# keep this list in sync with universe.apply_changes_with_patches.
SCAN_FORCING_KNOBS = ("PERITEXT_PATCH_PATH", "PERITEXT_MERGE_PATH")


@contextmanager
def patch_readback_env(mode: Optional[str] = None):
    """Pin the patch-record readback format (PERITEXT_PATCH_READBACK) for
    a measurement or differential leg.

    ``mode=None`` clears the knob (the compact default becomes active
    regardless of ambient CI env); ``"planes"`` / ``"compact"`` pin that
    format.  The caller's environment is restored on exit.
    """
    saved = os.environ.get("PERITEXT_PATCH_READBACK")
    os.environ.pop("PERITEXT_PATCH_READBACK", None)
    if mode:
        os.environ["PERITEXT_PATCH_READBACK"] = mode
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop("PERITEXT_PATCH_READBACK", None)
        else:
            os.environ["PERITEXT_PATCH_READBACK"] = saved


@contextmanager
def window_env(on: bool, min_cap: Optional[str] = None):
    """Pin the frontier-bounded window-merge knobs for one leg.

    ``on`` sets PERITEXT_MERGE_WINDOW (the windowed-vs-full A/B switch);
    ``min_cap`` optionally pins PERITEXT_MERGE_WINDOW_MIN (tests lower it
    so small documents engage).  Also clears the scan-forcing knobs — a
    windowed leg measured under an ambient PERITEXT_MERGE_PATH=scan would
    silently measure the scan path.  Restores the caller's env on exit.
    """
    saved = {
        k: os.environ.get(k)
        for k in ("PERITEXT_MERGE_WINDOW", "PERITEXT_MERGE_WINDOW_MIN")
    }
    os.environ["PERITEXT_MERGE_WINDOW"] = "1" if on else "0"
    if min_cap is not None:
        os.environ["PERITEXT_MERGE_WINDOW_MIN"] = min_cap
    try:
        with patch_path_env(None):
            yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@contextmanager
def patch_path_env(mode: Optional[str] = None):
    """Pin the patch-path selection for a measurement or differential leg.

    ``mode=None`` clears every scan-forcing knob (the sorted path becomes
    selectable regardless of ambient CI env); ``mode="scan"`` forces the
    interleaved scan.  The caller's environment is restored on exit.
    """
    saved = {k: os.environ.get(k) for k in SCAN_FORCING_KNOBS}
    for k in SCAN_FORCING_KNOBS:
        os.environ.pop(k, None)
    if mode:
        os.environ["PERITEXT_PATCH_PATH"] = mode
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def generate_docs(
    text: str = DEFAULT_TEXT, count: int = 2
) -> Tuple[List[Doc], List[List[Dict[str, Any]]], Dict[str, Any]]:
    """N synced replicas bootstrapped from a single genesis change.

    Reference test/generateDocs.ts:11-42: doc1 originates one change holding
    makeList + the initial insert; every other replica applies it, so all
    replicas share root structure (also the initializeDocs rule,
    bridge.ts:106-120).
    """
    docs = [Doc(f"doc{i + 1}") for i in range(count)]
    patches: List[List[Dict[str, Any]]] = [[] for _ in range(count)]
    initial_change, initial_patches = docs[0].change(
        [
            {"path": [], "action": "makeList", "key": "text"},
            {"path": ["text"], "action": "insert", "index": 0, "values": list(text)},
        ]
    )
    patches[0] = initial_patches
    for i in range(1, count):
        patches[i] = docs[i].apply_change(initial_change)
    return docs, patches, initial_change


def run_concurrent(
    *,
    initial_text: str = DEFAULT_TEXT,
    pre_ops: Optional[Sequence[Dict[str, Any]]] = None,
    input_ops1: Sequence[Dict[str, Any]] = (),
    input_ops2: Sequence[Dict[str, Any]] = (),
) -> Dict[str, Any]:
    """Concurrently apply two op sequences to two replicas and cross-sync.

    Reference test harness testConcurrentWrites (test/micromerge.ts:46-86).
    Returns the materialized spans from both replicas' batch codepaths and
    from both replicas' accumulated patch streams; callers assert all four
    equal the expected spans (the dual-path-equivalence invariant).
    """
    docs, patches, _ = generate_docs(initial_text)
    doc1, doc2 = docs
    patches1, patches2 = patches

    def with_path(ops: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        return [{**op, "path": ["text"]} for op in ops]

    if pre_ops:
        change0, p0 = doc1.change(with_path(pre_ops))
        patches1 = patches1 + p0
        patches2 = patches2 + doc2.apply_change(change0)

    change1, p1 = doc1.change(with_path(input_ops1))
    patches1 = patches1 + p1
    change2, p2 = doc2.change(with_path(input_ops2))
    patches2 = patches2 + p2

    patches2 = patches2 + doc2.apply_change(change1)
    patches1 = patches1 + doc1.apply_change(change2)

    return {
        "docs": (doc1, doc2),
        "batch1": doc1.get_text_with_formatting(["text"]),
        "batch2": doc2.get_text_with_formatting(["text"]),
        "patch1": accumulate_patches(patches1),
        "patch2": accumulate_patches(patches2),
        "patches": (patches1, patches2),
    }


def assert_converges(result: Dict[str, Any], expected: Sequence[Dict[str, Any]]) -> None:
    expected = list(expected)
    assert result["batch1"] == expected, f"doc1 batch: {result['batch1']} != {expected}"
    assert result["batch2"] == expected, f"doc2 batch: {result['batch2']} != {expected}"
    assert result["patch1"] == expected, f"doc1 patches: {result['patch1']} != {expected}"
    assert result["patch2"] == expected, f"doc2 patches: {result['patch2']} != {expected}"
