"""Editor-facing bridge: live editing sessions over the CRDT.

The equivalent of the reference's ProseMirror bridge (bridge.ts:198-344)
with the editor toolkit abstracted away: an :class:`Editor` wires a document
replica to an outbound :class:`ChangeQueue` and a shared :class:`Publisher`,
translates editor transactions into input operations
(`applyProsemirrorTransactionToMicromergeDoc`, bridge.ts:417-539), and
surfaces remote changes as incremental patches through a callback
(`extendProsemirrorTransactionWithMicromergePatch`, bridge.ts:132-195 — here
the callback consumes the framework's Patch dicts directly).

Editor "steps" mirror ProseMirror's step vocabulary:
- ``("replace", from_pos, to_pos, text)``  -> delete + insert input ops
- ``("add_mark", from_pos, to_pos, mark_type, attrs)`` -> addMark
- ``("remove_mark", from_pos, to_pos, mark_type, attrs)`` -> removeMark
"""
from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.oracle import Doc
from peritext_tpu.runtime import ChangeQueue, Publisher
from peritext_tpu.runtime.sync import apply_changes
from peritext_tpu.schema import MARK_SPEC

Patch = Dict[str, Any]
Step = Tuple


def describe_op(op: Dict[str, Any]) -> str:
    """Human-readable one-liner for an internal op (the live op-log panel,
    reference describeOp, bridge.ts:90-104)."""
    action = op.get("action")
    op_id = op.get("opId", "?")
    if action == "set" and op.get("insert"):
        after = op.get("elemId") or "HEAD"
        return f"{op_id}: insert {op.get('value')!r} after {after}"
    if action == "del":
        return f"{op_id}: delete {op.get('elemId')}"
    if action in ("addMark", "removeMark"):
        def side(b):
            if b.get("type") in ("startOfText", "endOfText"):
                return b["type"]
            return f"{b['type']}({b.get('elemId')})"

        attrs = f" {op['attrs']}" if op.get("attrs") else ""
        return (
            f"{op_id}: {action} {op.get('markType')}{attrs} "
            f"from {side(op['start'])} to {side(op['end'])}"
        )
    if action in ("makeList", "makeMap"):
        return f"{op_id}: {action} {op.get('key')!r}"
    if action == "set":
        return f"{op_id}: set {op.get('key')!r} = {op.get('value')!r}"
    return f"{op_id}: {action}"


class Comment:
    """Side-table entry for a comment body (reference comment.ts:1-12).

    The document stores only mark ids; comment content lives beside it.
    """

    __slots__ = ("id", "actor", "content")

    def __init__(self, comment_id: str, actor: str, content: str):
        self.id = comment_id
        self.actor = actor
        self.content = content

    def __repr__(self) -> str:  # pragma: no cover
        return f"Comment({self.id!r}, {self.actor!r}, {self.content!r})"


def initialize_docs(
    docs: Sequence[Doc], initial_ops: Optional[Sequence[Dict[str, Any]]] = None
) -> Dict[str, Any]:
    """Bootstrap replicas from a single genesis change on docs[0].

    Reference bridge.ts:106-120: all replicas share one makeList change so
    root structure can never diverge.
    """
    ops: List[Dict[str, Any]] = [{"path": [], "action": "makeList", "key": "text"}]
    if initial_ops:
        ops.extend(initial_ops)
    change, _ = docs[0].change(ops)
    for doc in docs[1:]:
        doc.apply_change(change)
    return change


class Editor:
    """One user's live editing session (reference createEditor, bridge.ts:198).

    Local steps -> input ops -> a local change (applied immediately, local
    patches surfaced) -> enqueued for batched publish.  Remote changes arrive
    via the publisher subscription, pass the causal gate, and surface as
    patches through ``on_patch`` / ``on_remote_patch``.
    """

    def __init__(
        self,
        doc: Doc,
        publisher: Publisher,
        *,
        interval: float = 0.01,
        editable: bool = True,
        on_patch: Optional[Callable[[Patch], None]] = None,
        on_remote_patch: Optional[Callable[[Patch], None]] = None,
    ) -> None:
        self.doc = doc
        self.publisher = publisher
        self.editable = editable
        self.on_patch = on_patch
        self.on_remote_patch = on_remote_patch
        self.comments: Dict[str, Comment] = {}
        self.change_log: List[Dict[str, Any]] = []
        self.queue = ChangeQueue(
            handle_flush=self._publish_changes, interval=interval
        )
        publisher.subscribe(doc.actor_id, self._receive_changes)

    # -- outbound ----------------------------------------------------------

    def _publish_changes(self, changes: List[Dict[str, Any]]) -> None:
        if changes:
            self.publisher.publish(self.doc.actor_id, changes)

    def apply_steps(self, steps: Sequence[Step]) -> List[Patch]:
        """Translate editor steps into one transactional change."""
        if not self.editable:
            raise PermissionError("editor is read-only")
        input_ops: List[Dict[str, Any]] = []
        for step in steps:
            input_ops.extend(self._step_to_ops(step))
        if not input_ops:
            return []
        change, patches = self.doc.change(input_ops)
        self.change_log.append(change)
        self.queue.enqueue(change)
        if self.on_patch:
            for patch in patches:
                self.on_patch(patch)
        return patches

    def _step_to_ops(self, step: Step) -> List[Dict[str, Any]]:
        kind = step[0]
        if kind == "replace":
            _, from_pos, to_pos, text = step
            ops: List[Dict[str, Any]] = []
            if to_pos > from_pos:
                ops.append(
                    {"path": ["text"], "action": "delete", "index": from_pos, "count": to_pos - from_pos}
                )
            if text:
                ops.append(
                    {"path": ["text"], "action": "insert", "index": from_pos, "values": list(text)}
                )
            return ops
        if kind in ("add_mark", "remove_mark"):
            _, from_pos, to_pos, mark_type, *rest = step
            attrs = rest[0] if rest else None
            if MARK_SPEC[mark_type].attr_keys and kind == "add_mark" and not attrs:
                raise ValueError(f"{mark_type} marks require attrs")
            op = {
                "path": ["text"],
                "action": "addMark" if kind == "add_mark" else "removeMark",
                "startIndex": from_pos,
                "endIndex": to_pos,
                "markType": mark_type,
            }
            if attrs:
                op["attrs"] = dict(attrs)
            return [op]
        raise ValueError(f"Unknown step kind: {kind}")

    # -- convenience commands (reference keymap, bridge.ts:35-68) -----------

    def insert(self, index: int, text: str) -> List[Patch]:
        return self.apply_steps([("replace", index, index, text)])

    def delete(self, index: int, count: int) -> List[Patch]:
        return self.apply_steps([("replace", index, index + count, "")])

    def toggle_mark(self, from_pos: int, to_pos: int, mark_type: str) -> List[Patch]:
        """Mod-B/Mod-I analog: add the boolean mark over the range."""
        return self.apply_steps([("add_mark", from_pos, to_pos, mark_type)])

    def add_comment(self, from_pos: int, to_pos: int, content: str) -> str:
        """Mod-E analog: comment with a fresh id; body goes to the side table."""
        comment_id = f"comment-{random.getrandbits(32):08x}"
        self.comments[comment_id] = Comment(comment_id, self.doc.actor_id, content)
        self.apply_steps([("add_mark", from_pos, to_pos, "comment", {"id": comment_id})])
        return comment_id

    def add_link(self, from_pos: int, to_pos: int, url: str) -> List[Patch]:
        """Mod-K analog."""
        return self.apply_steps([("add_mark", from_pos, to_pos, "link", {"url": url})])

    # -- inbound -----------------------------------------------------------

    def _receive_changes(self, changes: Sequence[Dict[str, Any]]) -> None:
        patches = apply_changes(self.doc, list(changes))
        for patch in patches:
            if self.on_patch:
                self.on_patch(patch)
            if self.on_remote_patch:
                self.on_remote_patch(patch)

    # -- views ---------------------------------------------------------------

    def spans(self) -> List[Dict[str, Any]]:
        return self.doc.get_text_with_formatting(["text"])

    def text(self) -> str:
        return "".join(self.doc.root.get("text", []))

    def sync(self) -> None:
        """Manual flush (the demo Sync button, index.ts:124-128)."""
        self.queue.flush()


class EditorNetwork:
    """A set of editors on one shared publisher (the live-demo topology)."""

    def __init__(self, actor_ids: Sequence[str], initial_text: str = "", **editor_kwargs):
        self.publisher: Publisher = Publisher()
        docs = [Doc(actor) for actor in actor_ids]
        initial_ops = (
            [{"path": ["text"], "action": "insert", "index": 0, "values": list(initial_text)}]
            if initial_text
            else None
        )
        self.genesis = initialize_docs(docs, initial_ops)
        self.editors: Dict[str, Editor] = {
            doc.actor_id: Editor(doc, self.publisher, **editor_kwargs) for doc in docs
        }

    def __getitem__(self, actor_id: str) -> Editor:
        return self.editors[actor_id]

    def sync_all(self) -> None:
        for editor in self.editors.values():
            editor.sync()

    def converged(self) -> bool:
        spans = [e.spans() for e in self.editors.values()]
        return all(s == spans[0] for s in spans[1:])
