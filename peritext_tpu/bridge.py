"""Editor-facing bridge: live editing sessions over the CRDT.

The equivalent of the reference's ProseMirror bridge (bridge.ts:198-344)
with the editor toolkit abstracted away: an :class:`Editor` wires a document
replica to an outbound :class:`ChangeQueue` and a shared :class:`Publisher`,
translates editor transactions into input operations
(`applyProsemirrorTransactionToMicromergeDoc`, bridge.ts:417-539), and
surfaces remote changes as incremental patches through a callback
(`extendProsemirrorTransactionWithMicromergePatch`, bridge.ts:132-195 — here
the callback consumes the framework's Patch dicts directly).

Editor "steps" mirror ProseMirror's step vocabulary:
- ``("replace", from_pos, to_pos, text)``  -> delete + insert input ops
- ``("add_mark", from_pos, to_pos, mark_type, attrs)`` -> addMark
- ``("remove_mark", from_pos, to_pos, mark_type, attrs)`` -> removeMark
"""
from __future__ import annotations

import logging
import random
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.oracle import Doc
from peritext_tpu.runtime import ChangeQueue, Publisher, faults
from peritext_tpu.runtime.sync import apply_available, apply_changes
from peritext_tpu.schema import MARK_SPEC

Patch = Dict[str, Any]
Step = Tuple

_log = logging.getLogger(__name__)


def describe_op(op: Dict[str, Any]) -> str:
    """Human-readable one-liner for an internal op (the live op-log panel,
    reference describeOp, bridge.ts:90-104)."""
    action = op.get("action")
    op_id = op.get("opId", "?")
    if action == "set" and op.get("insert"):
        after = op.get("elemId") or "HEAD"
        return f"{op_id}: insert {op.get('value')!r} after {after}"
    if action == "del":
        return f"{op_id}: delete {op.get('elemId')}"
    if action in ("addMark", "removeMark"):
        def side(b):
            if b.get("type") in ("startOfText", "endOfText"):
                return b["type"]
            return f"{b['type']}({b.get('elemId')})"

        attrs = f" {op['attrs']}" if op.get("attrs") else ""
        return (
            f"{op_id}: {action} {op.get('markType')}{attrs} "
            f"from {side(op['start'])} to {side(op['end'])}"
        )
    if action in ("makeList", "makeMap"):
        return f"{op_id}: {action} {op.get('key')!r}"
    if action == "set":
        return f"{op_id}: set {op.get('key')!r} = {op.get('value')!r}"
    return f"{op_id}: {action}"


class Comment:
    """Side-table entry for a comment body (reference comment.ts:1-12).

    The document stores only mark ids; comment content lives beside it.
    """

    __slots__ = ("id", "actor", "content")

    def __init__(self, comment_id: str, actor: str, content: str):
        self.id = comment_id
        self.actor = actor
        self.content = content

    def __repr__(self) -> str:  # pragma: no cover
        return f"Comment({self.id!r}, {self.actor!r}, {self.content!r})"


def initialize_docs(
    docs: Sequence[Doc], initial_ops: Optional[Sequence[Dict[str, Any]]] = None
) -> Dict[str, Any]:
    """Bootstrap replicas from a single genesis change on docs[0].

    Reference bridge.ts:106-120: all replicas share one makeList change so
    root structure can never diverge.
    """
    ops: List[Dict[str, Any]] = [{"path": [], "action": "makeList", "key": "text"}]
    if initial_ops:
        ops.extend(initial_ops)
    change, _ = docs[0].change(ops)
    for doc in docs[1:]:
        doc.apply_change(change)
    return change


class Editor:
    """One user's live editing session (reference createEditor, bridge.ts:198).

    Local steps -> input ops -> a local change (applied immediately, local
    patches surfaced) -> enqueued for batched publish.  Remote changes arrive
    via the publisher subscription, pass the causal gate, and surface as
    patches through ``on_patch`` / ``on_remote_patch``.
    """

    def __init__(
        self,
        doc: Doc,
        publisher: Publisher,
        *,
        interval: float = 0.01,
        editable: bool = True,
        on_patch: Optional[Callable[[Patch], None]] = None,
        on_remote_patch: Optional[Callable[[Patch], None]] = None,
        lock: Optional["threading.RLock"] = None,
    ) -> None:
        self.doc = doc
        self.publisher = publisher
        self.editable = editable
        self.on_patch = on_patch
        self.on_remote_patch = on_remote_patch
        self.comments: Dict[str, Comment] = {}
        self.change_log: List[Dict[str, Any]] = []
        # Causally-unready remote changes waiting for their dependencies
        # (the delivery retry buffer; see _receive_changes).
        self._pending: List[Dict[str, Any]] = []
        # Doc mutation guard for interval-driven mode: the queue timer
        # delivers remote changes on its own thread while the caller may be
        # mid-change() (or mid-read) on the same docs.  Defaults to the
        # PUBLISHER's lock so every editor on one publisher shares it by
        # construction — delivery happens inside a flush, so per-editor
        # locks would deadlock (A's flush holding A wanting B while B's
        # flush holds B wanting A).  RLock: a local change can publish
        # inline through its own flush.
        self.lock = lock if lock is not None else publisher.lock
        self.queue = ChangeQueue(
            handle_flush=self._publish_changes,
            interval=interval,
            flush_lock=self.lock,
            # Stable chaos stream key: injected queue_flush faults stay
            # per-editor and reproducible regardless of construction order.
            name=doc.actor_id,
        )
        publisher.subscribe(doc.actor_id, self._receive_changes)

    # -- outbound ----------------------------------------------------------

    def _publish_changes(self, changes: List[Dict[str, Any]]) -> None:
        if changes:
            with self.lock:
                self.publisher.publish(self.doc.actor_id, changes)

    def apply_steps(self, steps: Sequence[Step]) -> List[Patch]:
        """Translate editor steps into one transactional change."""
        if not self.editable:
            raise PermissionError("editor is read-only")
        input_ops: List[Dict[str, Any]] = []
        for step in steps:
            input_ops.extend(self._step_to_ops(step))
        if not input_ops:
            return []
        with self.lock:
            change, patches = self.doc.change(input_ops)
            self.change_log.append(change)
            self.queue.enqueue(change)
        if self.on_patch:
            for patch in patches:
                self.on_patch(patch)
        return patches

    def _step_to_ops(self, step: Step) -> List[Dict[str, Any]]:
        kind = step[0]
        if kind == "replace":
            _, from_pos, to_pos, text = step
            ops: List[Dict[str, Any]] = []
            if to_pos > from_pos:
                ops.append(
                    {"path": ["text"], "action": "delete", "index": from_pos, "count": to_pos - from_pos}
                )
            if text:
                ops.append(
                    {"path": ["text"], "action": "insert", "index": from_pos, "values": list(text)}
                )
            return ops
        if kind in ("add_mark", "remove_mark"):
            _, from_pos, to_pos, mark_type, *rest = step
            attrs = rest[0] if rest else None
            if MARK_SPEC[mark_type].attr_keys and kind == "add_mark" and not attrs:
                raise ValueError(f"{mark_type} marks require attrs")
            op = {
                "path": ["text"],
                "action": "addMark" if kind == "add_mark" else "removeMark",
                "startIndex": from_pos,
                "endIndex": to_pos,
                "markType": mark_type,
            }
            if attrs:
                op["attrs"] = dict(attrs)
            return [op]
        raise ValueError(f"Unknown step kind: {kind}")

    # -- convenience commands (reference keymap, bridge.ts:35-68) -----------

    def insert(self, index: int, text: str) -> List[Patch]:
        return self.apply_steps([("replace", index, index, text)])

    def delete(self, index: int, count: int) -> List[Patch]:
        return self.apply_steps([("replace", index, index + count, "")])

    def toggle_mark(self, from_pos: int, to_pos: int, mark_type: str) -> List[Patch]:
        """Mod-B/Mod-I analog: add the boolean mark over the range."""
        return self.apply_steps([("add_mark", from_pos, to_pos, mark_type)])

    def add_comment(self, from_pos: int, to_pos: int, content: str) -> str:
        """Mod-E analog: comment with a fresh id; body goes to the side table."""
        comment_id = f"comment-{random.getrandbits(32):08x}"
        self.comments[comment_id] = Comment(comment_id, self.doc.actor_id, content)
        self.apply_steps([("add_mark", from_pos, to_pos, "comment", {"id": comment_id})])
        return comment_id

    def add_link(self, from_pos: int, to_pos: int, url: str) -> List[Patch]:
        """Mod-K analog."""
        return self.apply_steps([("add_mark", from_pos, to_pos, "link", {"url": url})])

    # -- inbound -----------------------------------------------------------

    def _receive_changes(self, changes: Sequence[Dict[str, Any]]) -> None:
        err: Optional[BaseException] = None
        with self.lock:
            # Gap-tolerant retry buffer (the reference applyChanges queue,
            # test/merge.ts:4-23, kept across deliveries): under chaotic
            # delivery a dropped or held-back change must not turn every
            # later publish into an exception inside the subscriber callback
            # — causally-unready changes wait here and apply as soon as
            # their dependencies arrive; duplicates drop idempotently.
            queued = self._pending + list(changes)
            try:
                patches, self._pending = apply_available(self.doc, queued)
            except Exception as exc:
                # A non-causal mid-batch failure: changes before the failing
                # one DID apply — their patches (tagged on the exception by
                # apply_available) must still reach the view callbacks, or a
                # patch-driven consumer permanently misses content the doc
                # now contains (redelivery dedupes them).  The unapplied
                # remainder stays buffered for the next delivery — EXCEPT a
                # permanently-failing (non-transient) poison change, which
                # sits at the buffer's head and would otherwise wedge the
                # whole inbound path forever; it is dropped and surfaced.
                patches = list(getattr(exc, "applied_patches", ()))
                remaining = list(getattr(exc, "unapplied", queued))
                if (
                    remaining
                    and hasattr(exc, "unapplied")
                    and not faults.retryable(exc)
                ):
                    poison = remaining.pop(0)
                    _log.warning(
                        "dropping permanently-failing change %s@%s from the "
                        "delivery buffer (%s: %s)",
                        poison.get("actor"),
                        poison.get("seq"),
                        type(exc).__name__,
                        exc,
                    )
                self._pending = remaining
                err = exc
        for patch in patches:
            if self.on_patch:
                self.on_patch(patch)
            if self.on_remote_patch:
                self.on_remote_patch(patch)
        if err is not None:
            raise err

    # -- views ---------------------------------------------------------------

    def spans(self) -> List[Dict[str, Any]]:
        with self.lock:
            return self.doc.get_text_with_formatting(["text"])

    def text(self) -> str:
        with self.lock:
            return "".join(self.doc.root.get("text", []))

    def sync(self) -> None:
        """Manual flush (the demo Sync button, index.ts:124-128)."""
        self.queue.flush()


# -- editor document model (the reference's node schema + doc builder) -------
#
# Reference schema.ts:10-20 declares ``doc > paragraph+ > text*`` and
# bridge.ts:394-414 (prosemirrorDocFromCRDT) builds the editor document from
# the CRDT spans; bridge.ts:355-362 maps editor positions to content
# positions.  The toolkit is abstracted, so the document is plain dicts with
# the same node shapes.

NODE_SCHEMA = {"doc": ("paragraph+",), "paragraph": ("text*",)}


def editor_doc_from_spans(spans: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Build the editor document tree from formatted spans.

    Paragraph breaks are newline characters in the text stream (the
    reference renders one paragraph because its demo content has none);
    each paragraph holds text nodes carrying their mark maps.  An empty
    document is a single empty paragraph (the reference's empty-doc special
    case, bridge.ts:402-407).
    """
    paragraphs: List[List[Dict[str, Any]]] = [[]]
    for span in spans:
        parts = span["text"].split("\n")
        for i, part in enumerate(parts):
            if i > 0:
                paragraphs.append([])
            if part:
                paragraphs[-1].append(
                    {"type": "text", "text": part, "marks": dict(span["marks"])}
                )
    return {
        "type": "doc",
        "content": [
            {"type": "paragraph", "content": para} for para in paragraphs
        ],
    }


def editor_doc_text(doc: Dict[str, Any]) -> str:
    """Inverse view: the document's plain text with paragraph breaks."""
    return "\n".join(
        "".join(node["text"] for node in para["content"])
        for para in doc["content"]
    )


def content_pos_from_editor_pos(pos: int, doc: Dict[str, Any]) -> int:
    """Editor position -> CRDT content index.

    The reference's contentPosFromProsemirrorPos (bridge.ts:355-362) is the
    single-paragraph special case (pos - 1, clamped — its demo content has
    no paragraph breaks).  The general mapping walks the node tree: each
    paragraph costs one opening and one closing token in editor-position
    space, while in content space paragraphs join with one newline
    character.  Out-of-range positions clamp to the document ends.
    """
    paragraphs = doc["content"]
    editor = 0  # editor position just before this paragraph's opening token
    content = 0  # content index of this paragraph's first character
    total = sum(
        sum(len(n["text"]) for n in p["content"]) for p in paragraphs
    ) + max(len(paragraphs) - 1, 0)
    for para in paragraphs:
        length = sum(len(n["text"]) for n in para["content"])
        start = editor + 1  # inside the paragraph, after its opening token
        if pos <= start + length:
            return min(content + max(pos - start, 0), total)
        editor += length + 2
        content += length + 1  # the separating newline
    return total


class RemoteChangeHighlighter:
    """Flash remote edits with temporary highlight marks.

    The reference essay demo (essay-demo.ts:47-75) hooks
    ``onRemotePatchApplied`` and overlays demo-only ``highlightChange``
    marks (schema.ts:99-121) on the *view* for a moment — the highlights
    never enter the CRDT.  This is that flow with the toolkit abstracted:
    remote patches record flash ranges, ``spans()`` renders the editor's
    spans with the highlight overlaid, and ``tick()`` expires flashes (the
    reference uses a timeout).
    """

    MARK = "highlightChange"

    def __init__(self, editor: Editor, duration_ticks: int = 1) -> None:
        # Note the overlay mark never enters the CRDT, so it is NOT
        # registered in the mark schema (registration is for marks that
        # produce mark *ops* — schema.register_mark_type covers that path).
        self.editor = editor
        self.duration = duration_ticks
        self.flashes: List[Dict[str, int]] = []
        # Map ranges through every patch (local and remote, the way PM maps
        # decorations through all transactions); record flashes on remote
        # ones.  Editor fires on_patch before on_remote_patch, so a remote
        # patch maps earlier flashes first, then records its own.
        self._prev_patch_hook = editor.on_patch
        self._prev_remote_hook = editor.on_remote_patch
        editor.on_patch = self._on_any_patch
        editor.on_remote_patch = self._on_remote_patch

    @staticmethod
    def _patch_range(patch: Patch) -> Optional[Tuple[int, int]]:
        action = patch.get("action")
        if action == "insert":
            return patch["index"], patch["index"] + len(patch["values"])
        if action in ("addMark", "removeMark"):
            return patch["startIndex"], patch["endIndex"]
        return None  # deletes leave nothing on screen to flash

    def _map_through(self, patch: Patch) -> None:
        """Remap recorded flash ranges through an incoming patch, the way
        the reference maps decorations through ProseMirror transactions —
        a later insert/delete in the same sync shifts earlier flashes."""
        action = patch.get("action")
        if action == "insert":
            at, n = patch["index"], len(patch["values"])
            for f in self.flashes:
                if f["start"] >= at:
                    f["start"] += n
                if f["end"] > at:
                    f["end"] += n
        elif action == "delete":
            at, n = patch["index"], patch.get("count", 1)
            for f in self.flashes:
                f["start"] -= min(n, max(0, f["start"] - at))
                f["end"] -= min(n, max(0, f["end"] - at))
            self.flashes = [f for f in self.flashes if f["end"] > f["start"]]

    def _on_any_patch(self, patch: Patch) -> None:
        if self._prev_patch_hook:
            self._prev_patch_hook(patch)
        self._map_through(patch)

    def _on_remote_patch(self, patch: Patch) -> None:
        if self._prev_remote_hook:
            self._prev_remote_hook(patch)
        rng = self._patch_range(patch)
        if rng and rng[1] > rng[0]:
            self.flashes.append({"start": rng[0], "end": rng[1], "ttl": self.duration})

    def tick(self) -> None:
        """Advance the flash clock; expired highlights disappear."""
        for flash in self.flashes:
            flash["ttl"] -= 1
        self.flashes = [f for f in self.flashes if f["ttl"] > 0]

    def spans(self) -> List[Dict[str, Any]]:
        """The editor's spans with active flashes overlaid (view-only)."""
        base = self.editor.spans()
        if not self.flashes:
            return base
        out: List[Dict[str, Any]] = []
        pos = 0
        for span in base:
            text = span["text"]
            # Split this span at every flash boundary inside it.
            cuts = {0, len(text)}
            for f in self.flashes:
                for edge in (f["start"], f["end"]):
                    if pos < edge < pos + len(text):
                        cuts.add(edge - pos)
            edges = sorted(cuts)
            for a, b in zip(edges, edges[1:]):
                lit = any(
                    f["start"] < pos + b and pos + a < f["end"] for f in self.flashes
                )
                marks = dict(span["marks"])
                if lit:
                    marks[self.MARK] = {"active": True}
                if out and out[-1]["marks"] == marks:
                    out[-1]["text"] += text[a:b]
                else:
                    out.append({"marks": marks, "text": text[a:b]})
            pos += len(text)
        return out


class EditorNetwork:
    """A set of editors on one shared publisher (the live-demo topology)."""

    def __init__(self, actor_ids: Sequence[str], initial_text: str = "", **editor_kwargs):
        self.publisher: Publisher = Publisher()
        docs = [Doc(actor) for actor in actor_ids]
        initial_ops = (
            [{"path": ["text"], "action": "insert", "index": 0, "values": list(initial_text)}]
            if initial_text
            else None
        )
        self.genesis = initialize_docs(docs, initial_ops)
        # Editors default to the shared publisher lock, so the whole fleet
        # serializes on one RLock by construction.
        self.editors: Dict[str, Editor] = {
            doc.actor_id: Editor(doc, self.publisher, **editor_kwargs) for doc in docs
        }

    def __getitem__(self, actor_id: str) -> Editor:
        return self.editors[actor_id]

    def sync_all(self) -> None:
        for editor in self.editors.values():
            editor.sync()

    def start_all(self) -> None:
        """Switch every editor to interval-driven flushing — the reference's
        latency simulator (changeQueue.ts:17-19: the flush interval is the
        simulated network delay; index.ts runs with it before the demo drops
        to manual sync)."""
        for editor in self.editors.values():
            editor.queue.start()

    def stop_all(self) -> None:
        """Back to manual-sync mode (queue.drop, index.ts:119-121)."""
        for editor in self.editors.values():
            editor.queue.drop()

    def converged(self) -> bool:
        spans = [e.spans() for e in self.editors.values()]
        return all(s == spans[0] for s in spans[1:])
