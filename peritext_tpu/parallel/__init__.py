"""Multi-chip scaling: replica-batch + sequence sharding over device meshes.

The reference's "distributed backend" is an in-process Publisher + vector
clock anti-entropy (SURVEY.md §2.4); at TPU scale the replica batch is the
parallel axis.  A universe's [R, ...] state shards across a
``jax.sharding.Mesh`` with the replica dimension as data parallelism and the
sequence (capacity) dimension optionally sharded for very long documents —
XLA GSPMD inserts the ICI collectives (prefix-scan exchanges, argmax
reductions) that the sequence-sharded kernels need.
"""
from peritext_tpu.parallel.shard import flatten_sources_sp, merge_step_sorted_sp, place_text_sp
from peritext_tpu.parallel.mesh import (
    make_mesh,
    mesh_slices,
    shard_states,
    sharded_apply,
    sharded_digest_reduce,
    state_sharding,
)

__all__ = [
    "make_mesh",
    "mesh_slices",
    "shard_states",
    "sharded_apply",
    "sharded_digest_reduce",
    "state_sharding",
    "flatten_sources_sp",
    "place_text_sp",
    "merge_step_sorted_sp",
]
