"""Multi-host scaling over DCN.

One JAX program spans hosts via ``jax.distributed``; the same
``(replica, seq)`` mesh from :mod:`peritext_tpu.parallel.mesh` then covers
every host's devices, with the replica axis laid out so intra-slice
communication (the sequence-parallel scan carries, if used) rides ICI and
only the cross-replica digest reductions cross DCN — replicas never
communicate during op application, so DCN carries almost nothing.

On the host side, the replication plumbing is already multi-host shaped:
change logs ship as native-codec bytes (runtime/log.py to_bytes/from_bytes)
over whatever transport connects the hosts, and each host's universe ingests
through the same causal gate.  This module provides the initialization and
a host-sharded universe helper; it cannot be exercised in this repo's
single-host image (the test suite covers the mesh path on a virtual
8-device mesh instead).
"""
from __future__ import annotations

from typing import Optional

import jax

from peritext_tpu.parallel.mesh import make_mesh


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host JAX program (idempotent).

    With TPU metadata available (GKE/GCE), bare ``jax.distributed.
    initialize()`` autodiscovers everything; otherwise pass coordinator
    address + process layout explicitly.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as err:
        # jax 0.9 raises "distributed.initialize should only be called once."
        if "only be called once" not in str(err) and "already initialized" not in str(err):
            raise


def global_mesh(seq_axis: int = 1) -> jax.sharding.Mesh:
    """A (replica, seq) mesh over every device of every host.

    The device order groups each host's local devices contiguously along the
    replica axis, so a replica shard never straddles hosts and sequence
    shards (which do communicate) stay on one host's ICI domain when
    ``seq_axis`` divides the local device count.
    """
    return make_mesh(jax.devices(), seq_axis=seq_axis)


def local_replica_slice(num_replicas: int) -> slice:
    """The [start, stop) replica-batch rows owned by this host, for building
    host-local state that jax.make_array_from_process_local_data assembles
    into the global batch.  The batch must divide evenly across hosts (the
    downstream even-split NamedSharding cannot represent a remainder); pad
    the batch to a multiple of process_count() otherwise."""
    n = jax.process_count()
    if num_replicas % n != 0:
        raise ValueError(
            f"replica batch of {num_replicas} must divide across {n} hosts; pad it"
        )
    per = num_replicas // n
    start = jax.process_index() * per
    return slice(start, start + per)


def assemble_global_states(local_states, global_shape_states, mesh) -> object:
    """Assemble per-host local [r_local, ...] state pytrees into one
    mesh-sharded global batch (wraps jax.make_array_from_process_local_data
    leaf-wise)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def leaf(local, global_leaf):
        spec = P("replica", *([None] * (local.ndim - 1)))
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), local, global_leaf.shape
        )

    return jax.tree.map(leaf, local_states, global_shape_states)
