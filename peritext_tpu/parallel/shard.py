"""Explicit sequence-parallel kernels with shard_map + manual collectives.

GSPMD already shards the jnp kernels over the ``seq`` axis automatically
(parallel/mesh.py); this module is the *explicit* formulation — the document
dimension split across chips with hand-placed collectives over ICI — for the
long-document regime where one replica's sequence spans a slice:

- the mark-inheritance carry (``getTextWithFormatting``'s left-to-right
  walk, peritext.ts:366-390) becomes: a local prefix resolution per shard, a
  one-element **halo exchange** to the right neighbor (``ppermute`` ring
  shift) for the after-slot of each shard's last element, and a shard-level
  prefix over "last defined boundary per shard" summaries (``all_gather``
  along the seq axis — S summaries of W words each, a few hundred bytes on
  the wire, vs. the O(C) state that stays put).

The result is bit-identical to the single-device ``flatten_sources``
(tests/test_shard_map.py) while the per-shard work and memory scale as C/S.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    # The 0.4.x check_rep pass has no replication rule for `while`, which
    # every kernel here loops with — disable it (the vma-era default check
    # on newer jax handles while fine and stays on).
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, **kwargs):
        kwargs.setdefault("check_rep", False)
        return _shard_map_impl(f, **kwargs)



def _row_at(rows: jax.Array, idx: jax.Array) -> jax.Array:
    """rows[idx] for a traced idx, safe for idx = -1 (returns zeros)."""
    safe = jnp.maximum(idx, 0)
    row = lax.dynamic_slice_in_dim(rows, safe, 1, axis=0)[0]
    return jnp.where(idx >= 0, row, jnp.zeros_like(row))


def _sharded_flatten_local(
    elem_deleted, bnd_def, bnd_mask, length, *, seq_size: int
):
    """Per-shard body: local resolution + halo + shard-prefix carry.

    Operates on one replica's local slice (c_local elements).  Uses the
    ``seq`` axis name for collectives.
    """
    c_local = elem_deleted.shape[0]
    shard = lax.axis_index("seq")
    elem_offset = shard * c_local
    ar_local = jnp.arange(c_local, dtype=jnp.int32)
    ar_global = elem_offset + ar_local
    live = ar_global < length

    before_def = bnd_def[0::2] & live
    after_def = bnd_def[1::2] & live
    before_rows = bnd_mask[0::2]
    after_rows = bnd_mask[1::2]

    # Halo exchange: each shard sends its last element's (after_def, after
    # row) to its right neighbor over the ICI ring; shard 0 receives zeros.
    perm = [(s, s + 1) for s in range(seq_size - 1)]
    halo_def = lax.ppermute(after_def[-1], "seq", perm)
    halo_row = lax.ppermute(after_rows[-1], "seq", perm)

    prev_after_def = jnp.concatenate([halo_def[None], after_def[:-1]])
    prev_after_rows = jnp.concatenate([halo_row[None], after_rows[:-1]])

    # Element-level decision (reference peritext.ts:372-376): own before
    # slot wins, else the previous element's after slot.
    d_has = before_def | prev_after_def
    d_rows = jnp.where(before_def[:, None], before_rows, prev_after_rows)

    # Local prefix: nearest deciding element at or left of each element.
    src = lax.cummax(jnp.where(d_has, ar_local, jnp.int32(-1)))
    local_rows = jax.vmap(lambda i: _row_at(d_rows, i))(src)
    local_has = src >= 0

    # Shard summary: this shard's last deciding row (if any), gathered
    # across the seq axis so each shard can take the nearest preceding one.
    last_idx = jnp.max(jnp.where(d_has, ar_local, jnp.int32(-1)))
    summary_row = _row_at(d_rows, last_idx)
    summary_has = last_idx >= 0
    all_rows = lax.all_gather(summary_row, "seq")  # [S, W]
    all_has = lax.all_gather(summary_has, "seq")  # [S]

    s_idx = jnp.arange(seq_size, dtype=jnp.int32)
    prev_shards = all_has & (s_idx < shard)
    pick = jnp.max(jnp.where(prev_shards, s_idx, jnp.int32(-1)))
    incoming_row = _row_at(all_rows, pick)
    incoming_has = pick >= 0

    mask = jnp.where(local_has[:, None], local_rows, incoming_row[None, :])
    has = local_has | incoming_has
    return mask, has


def _placement_round_local(carry, r, text_ops, round_of, ranks, char_buf,
                           *, halo: int, maxk: int, c_global: int, seq_size: int):
    """One sort-based placement round on this shard's slice of one replica.

    The sharded form of kernels._place_round: anchor resolution and the
    skip-run stop become local candidates reduced with ``lax.pmin`` over the
    ``seq`` ICI ring ([L]-sized messages); block ordering is replicated [L]
    math; the splice is a left-neighbor **halo exchange** (``ppermute`` of
    the last ``halo`` elements — elements only ever shift right, by at most
    the round's total insert budget) followed by purely local scatters.
    """
    ec, ea, dl, ch, oi, ln = carry
    c_local = ec.shape[0]
    shard = lax.axis_index("seq")
    lo = shard * c_local
    gpos = lo + jnp.arange(c_local, dtype=jnp.int32)
    big = jnp.int32(2 * c_global + 2)
    K = _K()

    kind = text_ops[:, K.K_KIND]
    active = round_of == r
    is_ins = active & ((kind == K.KIND_INSERT) | (kind == K.KIND_INSERT_RUN))
    is_run = kind == K.KIND_INSERT_RUN
    is_del = active & (kind == K.KIND_DELETE)
    alive = gpos < ln

    ref_ctr = text_ops[:, K.K_REF_CTR]
    ref_act = text_ops[:, K.K_REF_ACT]
    ref_match = (
        alive[None, :] & (ec[None, :] == ref_ctr[:, None]) & (ea[None, :] == ref_act[:, None])
    )  # [L, Cl]

    # Deletes are shard-local.
    dl = dl | (ref_match & is_del[:, None]).any(axis=0)

    # Reference element position: local min-candidate -> pmin over the ring.
    local_first = jnp.min(jnp.where(ref_match, gpos[None, :], big), axis=1)
    global_first = lax.pmin(local_first, "seq")  # [L]
    is_head = (ref_ctr == 0) & (ref_act == 0)
    # The unsharded path's argmax(all-False) == 0 fallback, reproduced.
    idx = jnp.where(is_head, jnp.int32(-1), jnp.where(global_first >= big, 0, global_first))

    # Skip-run stop: same local-candidate + pmin shape.
    ctr_i = text_ops[:, K.K_CTR]
    rank_i = ranks[text_ops[:, K.K_ACT]]
    elem_rank = ranks[ea]
    gt = (ec[None, :] > ctr_i[:, None]) | (
        (ec[None, :] == ctr_i[:, None]) & (elem_rank[None, :] > rank_i[:, None])
    )
    stop = (gpos[None, :] > idx[:, None]) & ~(alive[None, :] & gt)
    t_local = jnp.min(jnp.where(stop, gpos[None, :], big), axis=1)
    t = lax.pmin(t_local, "seq")
    t = jnp.where(t >= big, jnp.int32(c_global), t)

    # Block ordering: replicated [L]/[L, L] math, identical on every shard.
    k = jnp.where(is_run, text_ops[:, K.K_RUN_LEN], 1) * is_ins.astype(jnp.int32)
    id_gt = (ctr_i[None, :] > ctr_i[:, None]) | (
        (ctr_i[None, :] == ctr_i[:, None]) & (rank_i[None, :] > rank_i[:, None])
    )
    before = (t[None, :] < t[:, None]) | ((t[None, :] == t[:, None]) & id_gt)
    s = t + jnp.sum(k[None, :] * before.astype(jnp.int32), axis=1)

    # Halo exchange: elements only move rightward, by at most the round's
    # insert budget (<= halo), so each shard needs the ceil(halo / Cl)
    # whole shards to its left as splice sources — one ppermute hop per
    # shard-width of displacement.  Left-edge shards receive zeros for
    # hops that fall off the ring; those positions mask out via src_gpos.
    hops = min(-(-halo // c_local), seq_size - 1) if seq_size > 1 else 0
    region = hops * c_local

    def halo_of(x):
        parts = [
            lax.ppermute(x, "seq", [(i, i + d) for i in range(seq_size - d)])
            for d in range(hops, 0, -1)
        ]
        return jnp.concatenate(parts) if parts else x[:0]

    def splice_into_local(own, halo_vals, fill, block_vals):
        src = jnp.concatenate([halo_vals, own])  # [region + Cl]
        src_gpos = lo - region + jnp.arange(region + c_local, dtype=jnp.int32)
        src_ok = (src_gpos >= 0) & (src_gpos < ln)
        shift = jnp.sum(
            k[:, None] * (t[:, None] <= src_gpos[None, :]).astype(jnp.int32), axis=0
        )
        # Out-of-shard destinations park at c_local; NOTE negative indices
        # must be clamped explicitly — .at[] applies Python negative-index
        # wrapping before drop-mode bounds checking.
        dest_local = src_gpos + shift - lo
        dest_local = jnp.where(
            src_ok & (dest_local >= 0), dest_local, jnp.int32(c_local)
        )
        out = jnp.full(c_local, fill, own.dtype)
        out = out.at[dest_local].set(src, mode="drop")
        # Op blocks: replicated values, locally-intersected destinations.
        off = jnp.arange(maxk, dtype=jnp.int32)
        in_block = off[None, :] < k[:, None]
        dest_ops = s[:, None] + off[None, :] - lo
        dest_ops = jnp.where(
            in_block & (dest_ops >= 0), dest_ops, jnp.int32(c_local)
        )
        return out.at[dest_ops].set(block_vals, mode="drop")

    off = jnp.arange(maxk, dtype=jnp.int32)
    buf_idx = jnp.clip(text_ops[:, K.K_PAYLOAD, None] + off[None, :], 0, char_buf.shape[0] - 1)
    block_chars = jnp.where(is_run[:, None], char_buf[buf_idx], text_ops[:, K.K_PAYLOAD, None])
    block_ctr = ctr_i[:, None] + off[None, :]
    block_act = jnp.broadcast_to(text_ops[:, K.K_ACT, None], block_ctr.shape)
    zero_blk = jnp.zeros_like(block_ctr)

    new_carry = (
        splice_into_local(ec, halo_of(ec), 0, block_ctr),
        splice_into_local(ea, halo_of(ea), 0, block_act),
        splice_into_local(dl.astype(jnp.int32), halo_of(dl.astype(jnp.int32)), 0, zero_blk).astype(bool),
        splice_into_local(ch, halo_of(ch), 0, block_chars),
        splice_into_local(oi, halo_of(oi), -1, zero_blk - 1),
        ln + jnp.sum(k),
    )
    return new_carry


def _K():
    from peritext_tpu.ops import kernels

    return kernels


def place_text_sp(mesh: Mesh, halo: int, maxk: int):
    """shard_map-compiled sequence-parallel sort-based text placement.

    The explicit-collective long-document form of kernels.place_text_batch:
    per-shard work and memory scale as C/S while the cross-shard traffic is
    [L]-sized pmin reductions plus ceil(halo / (C/S)) shard-wide ppermute
    pulls per round.  ``halo`` must be >= the largest single-round insert
    budget (the caller buckets the batch's total inserted characters);
    displacements wider than a shard resolve through multi-hop pulls, up
    to the whole ring.  Returns a jitted fn mapping the batched element
    arrays + op tensors to (ec, ea, dl, ch, oi, length).
    """
    seq_size = mesh.shape["seq"]

    def per_replica(ec, ea, dl, ch, ln, text_ops, round_of, num_rounds, ranks, char_buf):
        c_local = ec.shape[0]
        shard = lax.axis_index("seq")
        oi = shard * c_local + jnp.arange(c_local, dtype=jnp.int32)
        # The initial orig-idx plane is seq-varying only; the loop mixes it
        # with replica-varying data, so align its varying axes up front.
        # (0.4.x-era shard_map has no varying-axes tracking at all — there
        # the mix needs no alignment and neither spelling exists.)
        if hasattr(lax, "pcast"):
            oi = lax.pcast(oi, ("replica",), to="varying")
        elif hasattr(lax, "pvary"):
            oi = lax.pvary(oi, ("replica",))
        carry = (ec, ea, dl, ch, oi, ln)
        carry = lax.fori_loop(
            0,
            num_rounds,
            lambda r, cry: _placement_round_local(
                cry, r, text_ops, round_of, ranks, char_buf,
                halo=halo, maxk=maxk, c_global=c_local * seq_size, seq_size=seq_size,
            ),
            carry,
        )
        return carry

    def batched(ec, ea, dl, ch, ln, text_ops, round_of, num_rounds, ranks, char_buf):
        return jax.vmap(
            per_replica, in_axes=(0, 0, 0, 0, 0, 0, 0, None, None, 0)
        )(ec, ea, dl, ch, ln, text_ops, round_of, num_rounds, ranks, char_buf)

    mapped = shard_map(
        batched,
        mesh=mesh,
        in_specs=(
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica"),
            P("replica", None, None),
            P("replica", None),
            P(),
            P(),
            P("replica", None),
        ),
        out_specs=(
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica"),
        ),
    )
    return jax.jit(mapped)


def merge_step_sorted_sp(mesh: Mesh, halo: int, maxk: int):
    """Full sorted merge for the long-document regime: explicit-collective
    text placement (place_text_sp) composed with the GSPMD-sharded tail
    (boundary permute + batched mark phase — gathers and [2C, M] matmuls
    that GSPMD partitions over the same mesh).  State-equivalent to
    kernels.merge_step_sorted on the gathered arrays.
    """
    K = _K()
    place = place_text_sp(mesh, halo=halo, maxk=maxk)

    def step(states, text_ops, round_of, num_rounds, mark_ops, ranks, char_buf):
        ec, ea, dl, ch, oi, ln = place(
            states.elem_ctr,
            states.elem_act,
            states.deleted,
            states.chars,
            states.length,
            text_ops,
            round_of,
            num_rounds,
            ranks,
            char_buf,
        )
        return jax.vmap(
            K._sorted_tail, in_axes=(0, 0, 0, 0, 0, 0, 0, 0)
        )(states, ec, ea, dl.astype(bool), ch, oi, ln, mark_ops)

    return jax.jit(step)


def flatten_sources_sp(mesh: Mesh):
    """shard_map-compiled sequence-parallel flatten over (replica, seq).

    Takes the batched raw arrays (deleted [R, C], bnd_def [R, 2C],
    bnd_mask [R, 2C, W], length [R]) and returns (mask [R, C, W],
    has [R, C]) identical to jax.vmap(kernels.flatten_sources).
    """
    seq_size = mesh.shape["seq"]

    def per_replica(deleted, bnd_def, bnd_mask, length):
        return _sharded_flatten_local(
            deleted, bnd_def, bnd_mask, length, seq_size=seq_size
        )

    def batched(deleted, bnd_def, bnd_mask, length):
        return jax.vmap(per_replica)(deleted, bnd_def, bnd_mask, length)

    mapped = shard_map(
        batched,
        mesh=mesh,
        in_specs=(
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq", None),
            P("replica"),
        ),
        out_specs=(P("replica", "seq", None), P("replica", "seq")),
    )
    return jax.jit(mapped)
