"""Explicit sequence-parallel kernels with shard_map + manual collectives.

GSPMD already shards the jnp kernels over the ``seq`` axis automatically
(parallel/mesh.py); this module is the *explicit* formulation — the document
dimension split across chips with hand-placed collectives over ICI — for the
long-document regime where one replica's sequence spans a slice:

- the mark-inheritance carry (``getTextWithFormatting``'s left-to-right
  walk, peritext.ts:366-390) becomes: a local prefix resolution per shard, a
  one-element **halo exchange** to the right neighbor (``ppermute`` ring
  shift) for the after-slot of each shard's last element, and a shard-level
  prefix over "last defined boundary per shard" summaries (``all_gather``
  along the seq axis — S summaries of W words each, a few hundred bytes on
  the wire, vs. the O(C) state that stays put).

The result is bit-identical to the single-device ``flatten_sources``
(tests/test_shard_map.py) while the per-shard work and memory scale as C/S.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map



def _row_at(rows: jax.Array, idx: jax.Array) -> jax.Array:
    """rows[idx] for a traced idx, safe for idx = -1 (returns zeros)."""
    safe = jnp.maximum(idx, 0)
    row = lax.dynamic_slice_in_dim(rows, safe, 1, axis=0)[0]
    return jnp.where(idx >= 0, row, jnp.zeros_like(row))


def _sharded_flatten_local(
    elem_deleted, bnd_def, bnd_mask, length, *, seq_size: int
):
    """Per-shard body: local resolution + halo + shard-prefix carry.

    Operates on one replica's local slice (c_local elements).  Uses the
    ``seq`` axis name for collectives.
    """
    c_local = elem_deleted.shape[0]
    shard = lax.axis_index("seq")
    elem_offset = shard * c_local
    ar_local = jnp.arange(c_local, dtype=jnp.int32)
    ar_global = elem_offset + ar_local
    live = ar_global < length

    before_def = bnd_def[0::2] & live
    after_def = bnd_def[1::2] & live
    before_rows = bnd_mask[0::2]
    after_rows = bnd_mask[1::2]

    # Halo exchange: each shard sends its last element's (after_def, after
    # row) to its right neighbor over the ICI ring; shard 0 receives zeros.
    perm = [(s, s + 1) for s in range(seq_size - 1)]
    halo_def = lax.ppermute(after_def[-1], "seq", perm)
    halo_row = lax.ppermute(after_rows[-1], "seq", perm)

    prev_after_def = jnp.concatenate([halo_def[None], after_def[:-1]])
    prev_after_rows = jnp.concatenate([halo_row[None], after_rows[:-1]])

    # Element-level decision (reference peritext.ts:372-376): own before
    # slot wins, else the previous element's after slot.
    d_has = before_def | prev_after_def
    d_rows = jnp.where(before_def[:, None], before_rows, prev_after_rows)

    # Local prefix: nearest deciding element at or left of each element.
    src = lax.cummax(jnp.where(d_has, ar_local, jnp.int32(-1)))
    local_rows = jax.vmap(lambda i: _row_at(d_rows, i))(src)
    local_has = src >= 0

    # Shard summary: this shard's last deciding row (if any), gathered
    # across the seq axis so each shard can take the nearest preceding one.
    last_idx = jnp.max(jnp.where(d_has, ar_local, jnp.int32(-1)))
    summary_row = _row_at(d_rows, last_idx)
    summary_has = last_idx >= 0
    all_rows = lax.all_gather(summary_row, "seq")  # [S, W]
    all_has = lax.all_gather(summary_has, "seq")  # [S]

    s_idx = jnp.arange(seq_size, dtype=jnp.int32)
    prev_shards = all_has & (s_idx < shard)
    pick = jnp.max(jnp.where(prev_shards, s_idx, jnp.int32(-1)))
    incoming_row = _row_at(all_rows, pick)
    incoming_has = pick >= 0

    mask = jnp.where(local_has[:, None], local_rows, incoming_row[None, :])
    has = local_has | incoming_has
    return mask, has


def flatten_sources_sp(mesh: Mesh):
    """shard_map-compiled sequence-parallel flatten over (replica, seq).

    Takes the batched raw arrays (deleted [R, C], bnd_def [R, 2C],
    bnd_mask [R, 2C, W], length [R]) and returns (mask [R, C, W],
    has [R, C]) identical to jax.vmap(kernels.flatten_sources).
    """
    seq_size = mesh.shape["seq"]

    def per_replica(deleted, bnd_def, bnd_mask, length):
        return _sharded_flatten_local(
            deleted, bnd_def, bnd_mask, length, seq_size=seq_size
        )

    def batched(deleted, bnd_def, bnd_mask, length):
        return jax.vmap(per_replica)(deleted, bnd_def, bnd_mask, length)

    mapped = shard_map(
        batched,
        mesh=mesh,
        in_specs=(
            P("replica", "seq"),
            P("replica", "seq"),
            P("replica", "seq", None),
            P("replica"),
        ),
        out_specs=(P("replica", "seq", None), P("replica", "seq")),
    )
    return jax.jit(mapped)
