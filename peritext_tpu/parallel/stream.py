"""Streaming replica cohorts: aggregate replica count decoupled from HBM.

The north-star shape (BASELINE.json config 5: 100k replicas x 10k-char
docs) does not fit resident on a v5e-8 — the HBM budget table in
BASELINE.md caps residency at ~27.7k replicas at C=16384/M=1024.  This
module is the route past that wall: the full replica population lives in
host memory as numpy arrays, and fixed-size *cohorts* stream through the
device mesh.  Each cohort is device_put (async H2D DMA), merged with a
buffer-donating jitted step, digested on device, and read back — with the
next cohort's transfer and dispatch issued *before* the previous cohort's
readback, so on real hardware the H2D/D2H DMAs overlap the merge compute
(JAX dispatch is asynchronous; the readback `np.asarray` is the only
barrier, which on the axon relay is also the only honest one).

Device residency is bounded by the pipeline depth (two cohorts in flight)
times the cohort footprint, independent of the aggregate replica count:

    resident bytes ~= depth * cohort * (state_bytes + transient_bytes)

where the merge transients are O(L*C + M*2C) per replica (kernels.py,
merge_step_sorted_batch docstring).  `cohort_for_budget` sizes a cohort
from that estimate; `stream_merge_sorted` is bit-identical to the resident
single-launch merge (tests/test_stream.py digest- and state-compares the
two at shapes that fit both ways).

This is the multi-replica generalization of the PERITEXT_SORTED_CHUNK
valve: the valve re-launches over slices of a *device-resident* batch to
bound transients; the stream bounds *state* residency too, holding the
population on host.  The reference has no counterpart — its replicas are
one JS heap each (micromerge.ts holds a single document); population
scale-out is exactly what the TPU redesign adds.
"""
from __future__ import annotations

import functools
from collections import deque
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from peritext_tpu.ops import kernels as K
from peritext_tpu.ops.state import DocState, make_empty_state
from peritext_tpu.parallel.mesh import state_sharding
from peritext_tpu.runtime import faults, health, telemetry
from peritext_tpu.schema import allow_multiple_array


def state_bytes_per_replica(capacity: int, max_mark_ops: int) -> int:
    """Exact DocState bytes for one replica at the given shape."""
    proto = make_empty_state(capacity, max_mark_ops)
    return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(proto))


def transient_bytes_per_replica(capacity: int, max_mark_ops: int, ops_len: int) -> int:
    """Upper-bound merge transients per replica: the placement phase holds
    O(L*C) int32 compare planes and the mark phase O(L_mark*2C) planes,
    where L is the op-batch length (a batch's mark rows are bounded by its
    op count, NOT the table capacity M).  ~3 L*C-sized int32 planes total —
    within 2x of the compiler-measured temp HBM at the bench shape
    (PROFILE_r04.md: 653 MiB / 256 replicas = 2.55 MiB at C=2048, L=64;
    this returns 1.5 MiB, scaled by the 2x safety factor below)."""
    del max_mark_ops  # table capacity does not size the transients
    return 2 * 4 * (ops_len * capacity + ops_len * 2 * capacity)


def cohort_for_budget(
    capacity: int,
    max_mark_ops: int,
    ops_len: int,
    hbm_bytes: int = 16 * 2**30,
    headroom: float = 0.9,
    depth: int = 2,
    n_devices: int = 1,
) -> int:
    """Largest cohort whose ``depth`` in-flight copies fit the HBM budget
    (per device; a mesh multiplies the budget by its device count)."""
    per = state_bytes_per_replica(capacity, max_mark_ops) + transient_bytes_per_replica(
        capacity, max_mark_ops, ops_len
    )
    return max(1, int(hbm_bytes * headroom * n_devices / (depth * per)))


@functools.lru_cache(maxsize=None)
def _stream_step(maxk: int, compute_digest: bool):
    """Jitted cohort step: sorted merge + optional digest, donating the
    input state buffers so device residency stays at one cohort copy per
    pipeline slot (the resident-path jit must NOT donate — benches and the
    universe reuse their input states across launches)."""
    merge = jax.vmap(
        functools.partial(K.merge_step_sorted, maxk=maxk),
        in_axes=(0, 0, 0, None, 0, None, 0),
    )

    def step(states, text_ops, round_of, num_rounds, mark_ops, ranks, char_buf, multi):
        out = merge(states, text_ops, round_of, num_rounds, mark_ops, ranks, char_buf)
        if compute_digest:
            dg = jax.vmap(K.convergence_digest, in_axes=(0, None, None))(
                out, ranks, multi
            )
        else:
            # Not a digest — a completion token: it must DEPEND on the merge
            # output so the drain-side readback is a real barrier even when
            # the caller skips both digests and state readback.
            dg = out.length.astype(jnp.uint32)
        return out, dg

    return jax.jit(step, donate_argnums=(0,))


def _cohort_shardings(mesh: Optional[Mesh], shard_seq: bool):
    """(state sharding, [R,...] op sharding) for device_put, or Nones."""
    if mesh is None:
        return None, None
    return state_sharding(mesh, shard_seq), NamedSharding(mesh, P("replica"))


def stream_merge_sorted(
    states: DocState,
    text_ops: np.ndarray,
    round_of: np.ndarray,
    num_rounds: int,
    mark_ops: np.ndarray,
    ranks: np.ndarray,
    char_buf: np.ndarray,
    maxk: int,
    cohort: int,
    mesh: Optional[Mesh] = None,
    shard_seq: bool = True,
    compute_digest: bool = True,
    readback_states: bool = True,
    depth: int = 2,
) -> Tuple[Optional[DocState], np.ndarray, Dict[str, Any]]:
    """Merge an arbitrarily large replica population by streaming cohorts.

    ``states`` and the op tensors are host-resident (numpy) with leading
    dim R_total; at most ``depth`` cohorts of ``cohort`` replicas are on
    device at once.  Returns (updated host states | None, digests [R_total],
    stats).  The tail cohort is padded by repeating replica 0 (one compiled
    program shape); padded lanes are dropped on readback.  With
    ``compute_digest=False`` the digest slot carries post-merge lengths
    instead (a cheap completion token, not a convergence digest).
    """
    r_total = int(text_ops.shape[0])
    if cohort <= 0:
        raise ValueError(f"cohort must be positive, got {cohort}")
    if mesh is None:
        cohort = min(cohort, r_total)
    else:
        # Cohorts must divide over the replica mesh axis; round up (the tail
        # pad already fills partial cohorts, so over-sizing is harmless and
        # keeps one compiled program shape).
        axis = int(mesh.shape["replica"])
        cohort = min(cohort, -(-r_total // axis) * axis)
        cohort = -(-cohort // axis) * axis
    state_shd, ops_shd = _cohort_shardings(mesh, shard_seq)
    step = _stream_step(maxk, compute_digest)
    nr = jnp.int32(num_rounds)
    ranks_d = jax.device_put(
        jnp.asarray(ranks), NamedSharding(mesh, P()) if mesh is not None else None
    )
    multi_d = jax.device_put(
        jnp.asarray(allow_multiple_array()),
        NamedSharding(mesh, P()) if mesh is not None else None,
    )

    host_states = jax.tree.map(np.asarray, states)
    out_states = (
        jax.tree.map(np.empty_like, host_states) if readback_states else None
    )
    digests = np.zeros((r_total,), np.uint32)

    def pad(arr, lo, hi):
        """Cohort slice [lo:hi), padded to `cohort` rows with replica 0."""
        sl = arr[lo:hi]
        if hi - lo == cohort:
            return sl
        fill = np.broadcast_to(arr[0:1], (cohort - (hi - lo),) + arr.shape[1:])
        return np.concatenate([sl, fill], axis=0)

    def _record(br, exc: BaseException) -> None:
        # Transient errors are breaker signal; semantic errors and
        # BaseExceptions (KeyboardInterrupt mid-sweep) are not — but they
        # must still release a held canary slot, or the breaker would
        # fast-fail forever with no probe able to run.
        if br is not None:
            if faults.retryable(exc):
                br.record_failure()
            else:
                br.abandon()

    def launch(lo: int):
        # The launch span covers H2D device_put + async dispatch only; the
        # matching drain span covers the D2H readback barrier.  In a trace,
        # launch spans overlapping earlier cohorts' drain spans IS the
        # pipeline overlap the depth>1 design claims.
        #
        # Health gating: the stream has no oracle degrade path (the whole
        # point is a population too big to re-apply host-side per cohort),
        # so an OPEN device_launch breaker fast-fails the sweep immediately
        # with BreakerOpenError — the caller retries the round once the
        # circuit recovers.  Outcomes feed the breaker at the honest
        # barrier: success on drain readback, failure on a launch OR drain
        # exception.
        br = health.breaker("device_launch")
        decision = health.ALLOW if br is None else br.admit()
        if decision == health.FASTFAIL:
            if telemetry.enabled:
                telemetry.record("stream.launch", outcome="fastfail", lo=lo)
            raise health.BreakerOpenError("device_launch")
        hi = min(lo + cohort, r_total)
        # One causal lane per cohort: start at launch (H2D + dispatch),
        # finish at drain (the readback barrier) — in Perfetto the lanes'
        # arrows crossing each other ARE the pipeline overlap the depth>1
        # design claims.
        ctx = telemetry.flow("stream.cohort", lo=lo, hi=hi) if telemetry.enabled else None
        with telemetry.span("stream.launch", lo=lo, hi=hi):
            telemetry.flow_point(ctx)
            try:
                faults.fire("device_launch")
                st = jax.tree.map(lambda a: pad(a, lo, hi), host_states)
                st_d = (
                    jax.tree.map(jax.device_put, st, state_shd)
                    if state_shd is not None
                    else jax.tree.map(jax.device_put, st)
                )
                puts = [
                    jax.device_put(pad(a, lo, hi), ops_shd)
                    for a in (text_ops, round_of, mark_ops, char_buf)
                ]
                out, dg = step(
                    st_d, puts[0], puts[1], nr, puts[2], ranks_d, puts[3], multi_d
                )
            except BaseException as exc:
                _record(br, exc)
                if telemetry.enabled:
                    telemetry.record(
                        "stream.launch", flow=ctx, outcome="error",
                        error=type(exc).__name__,
                    )
                # The lane ends here — an unterminated flow would read as
                # a lost cohort.
                telemetry.flow_point(ctx, terminal=True, outcome="error")
                raise
        return lo, hi, out, dg, br, decision, ctx

    def drain(entry):
        lo, hi, out, dg, br, _decision, ctx = entry
        with telemetry.span("stream.drain", lo=lo, hi=hi):
            n = hi - lo
            try:
                faults.fire("device_readback")
                digests[lo:hi] = np.asarray(dg)[:n]
                if out_states is not None:
                    for host_leaf, dev_leaf in zip(
                        jax.tree.leaves(out_states), jax.tree.leaves(out)
                    ):
                        host_leaf[lo:hi] = np.asarray(dev_leaf)[:n]
                else:
                    # Digest readback above is the completion barrier already.
                    del out
            except BaseException as exc:
                _record(br, exc)
                if telemetry.enabled:
                    telemetry.record(
                        "stream.drain", flow=ctx, outcome="error",
                        error=type(exc).__name__,
                    )
                telemetry.flow_point(ctx, terminal=True, outcome="error")
                raise
            # Lane terminal: the readback completed — the cohort is done.
            telemetry.flow_point(ctx, terminal=True)
        if ctx is not None:
            telemetry.observe(
                "e2e.cohort_launch_to_drain", telemetry.flow_elapsed_s(ctx)
            )
        if br is not None:
            br.record_success()

    inflight: deque = deque()
    n_cohorts = 0
    try:
        for lo in range(0, r_total, cohort):
            entry = launch(lo)
            n_cohorts += 1
            if entry[5] == health.CANARY:  # the admit() decision slot
                # A half-open probe must resolve (drain = the honest readback
                # barrier) before any further cohort is admitted: its success
                # closes the circuit for the rest of the sweep, its failure
                # re-opens — either way the next admit() sees the verdict
                # instead of fast-failing behind a still-in-flight canary.
                drain(entry)
                if telemetry.enabled:
                    telemetry.counter("stream.cohorts")
                continue
            inflight.append(entry)
            if telemetry.enabled:
                telemetry.counter("stream.cohorts")
                telemetry.gauge_max("stream.inflight_max", len(inflight))
            # Keep `depth` cohorts in flight: the next cohort's H2D and merge
            # are dispatched (async) before this readback blocks, so the DMA
            # engines overlap the compute on hardware.
            while len(inflight) >= depth:
                drain(inflight.popleft())
        while inflight:
            drain(inflight.popleft())
    except BaseException:
        # A mid-sweep abort (failed drain, breaker fast-fail, Ctrl-C)
        # leaves launched-but-undrained cohorts in the window; their lanes
        # must still end or the trace reads them as lost.
        if telemetry.enabled and inflight:
            with telemetry.span("stream.abort", pending=len(inflight)):
                for entry in inflight:
                    telemetry.flow_point(entry[6], terminal=True, outcome="abort")
        raise

    stats = {
        "replicas": r_total,
        "cohort": cohort,
        "n_cohorts": n_cohorts,
        "depth": depth,
        "padded_tail": (r_total % cohort) != 0,
    }
    return out_states, digests, stats
