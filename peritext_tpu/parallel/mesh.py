"""Device-mesh construction and sharding specs for batched replica state.

Axes:
- ``replica``: data parallelism over the replica batch (the throughput axis;
  BASELINE.json configs 3-5).  Merges are replica-local, so this axis needs
  no communication during op application.
- ``seq``: optional sequence parallelism over the document capacity
  dimension, for long documents.  The kernels are pure jnp index arithmetic
  + prefix scans, so GSPMD shards them over ``seq`` by inserting ICI
  collectives (segmented-scan carries, argmax all-reduces) automatically.

Cross-replica reductions (convergence digests) ride ``psum``-style
all-reduces over the mesh; across hosts the same program spans DCN via
standard multi-host jax.distributed initialization.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from peritext_tpu.ops import kernels as K
from peritext_tpu.ops.state import DocState


def mesh_slices(
    n_shards: int, devices: Optional[Sequence[jax.Device]] = None
) -> list:
    """Partition the device mesh into ``n_shards`` serving slices.

    The sharded serving plane (runtime/serve_shard.py) runs one universe
    shard per slice.  With shards <= devices each slice is a contiguous
    device group (remainder devices land on the leading slices, so slice
    sizes differ by at most one and a pow2 shard count over a pow2 mesh
    tiles exactly); with more shards than devices, slices are singleton
    and round-robin over the mesh — shards share chips but keep their
    own universes/schedulers.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)
    if n_shards >= n_dev:
        return [[devices[i % n_dev]] for i in range(n_shards)]
    base, extra = divmod(n_dev, n_shards)
    slices = []
    lo = 0
    for i in range(n_shards):
        hi = lo + base + (1 if i < extra else 0)
        slices.append(devices[lo:hi])
        lo = hi
    return slices


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    replica_axis: Optional[int] = None,
    seq_axis: int = 1,
) -> Mesh:
    """Build a (replica, seq) mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if replica_axis is None:
        replica_axis = n // seq_axis
    if replica_axis * seq_axis != n:
        raise ValueError(f"mesh {replica_axis}x{seq_axis} != {n} devices")
    arr = np.array(devices).reshape(replica_axis, seq_axis)
    return Mesh(arr, ("replica", "seq"))


def state_sharding(mesh: Mesh, shard_seq: bool = True) -> DocState:
    """A DocState-shaped pytree of NamedShardings for batched [R, ...] state.

    The replica batch dim shards over ``replica``; the capacity dims (C and
    2C) shard over ``seq`` when requested; the mark table replicates within a
    replica shard (it is small and consulted by every sequence position).
    """
    seq = "seq" if shard_seq else None

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return DocState(
        elem_ctr=ns("replica", seq),
        elem_act=ns("replica", seq),
        deleted=ns("replica", seq),
        chars=ns("replica", seq),
        bnd_def=ns("replica", seq),
        bnd_mask=ns("replica", seq, None),
        mark_ctr=ns("replica", None),
        mark_act=ns("replica", None),
        mark_action=ns("replica", None),
        mark_type=ns("replica", None),
        mark_attr=ns("replica", None),
        length=ns("replica"),
        mark_count=ns("replica"),
    )


def shard_states(states: DocState, mesh: Mesh, shard_seq: bool = True) -> DocState:
    shardings = state_sharding(mesh, shard_seq)
    return jax.tree.map(jax.device_put, states, shardings)


def _apply_and_digest(states: DocState, text_ops: jax.Array, mark_ops: jax.Array, ranks: jax.Array, multi: jax.Array):
    """One full sharded step: batched fast merge + global convergence.

    The jnp.sum over per-replica digests lowers to an all-reduce across the
    ``replica`` mesh axis; the sequence-sharded kernels inside get their
    carry/argmax collectives from GSPMD.
    """
    new_states = K.merge_step_vmapped(states, text_ops, mark_ops, ranks)
    digests = jax.vmap(K.convergence_digest, in_axes=(0, None, None))(new_states, ranks, multi)
    global_digest = jnp.sum(digests)
    return new_states, digests, global_digest


def sharded_apply(mesh: Mesh, shard_seq: bool = True):
    """jit-compile the full step with explicit mesh shardings."""
    st_shard = state_sharding(mesh, shard_seq)
    ops_shard = NamedSharding(mesh, P("replica", None, None))
    ranks_shard = NamedSharding(mesh, P())
    digest_shard = NamedSharding(mesh, P("replica"))
    return jax.jit(
        _apply_and_digest,
        in_shardings=(st_shard, ops_shard, ops_shard, ranks_shard, ranks_shard),
        out_shardings=(st_shard, digest_shard, NamedSharding(mesh, P())),
    )


def sharded_digest_reduce(mesh: Mesh, shard_seq: bool = True):
    """Batched digest computation + global reduce under mesh shardings."""
    st_shard = state_sharding(mesh, shard_seq)

    def f(states: DocState, ranks: jax.Array, multi: jax.Array):
        digests = jax.vmap(K.convergence_digest, in_axes=(0, None, None))(states, ranks, multi)
        return digests, jnp.sum(digests)

    return jax.jit(
        f,
        in_shardings=(st_shard, NamedSharding(mesh, P()), NamedSharding(mesh, P())),
        out_shardings=(NamedSharding(mesh, P("replica")), NamedSharding(mesh, P())),
    )
