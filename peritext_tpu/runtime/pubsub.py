"""In-process publish/subscribe fan-out.

Reference: /root/reference/src/pubsub.ts:1-26 (Publisher).  ``publish`` fans an
update out to every subscriber except the sender.

Delivery runs through the ``pubsub_deliver`` fault site (runtime/faults.py):
an active chaos plan can drop, duplicate, delay (wedge) or fail deliveries
per subscriber, and hold messages back for reordering — held messages
re-emerge ahead of later publishes to the same subscriber, so causal-gap
recovery (anti-entropy sync) is what restores convergence, exactly the
adversarial delivery model the CRDT claims to tolerate.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, TypeVar

from peritext_tpu.runtime import faults
from peritext_tpu.runtime import telemetry

T = TypeVar("T")


class Publisher(Generic[T]):
    def __init__(self) -> None:
        self._subscribers: Dict[str, Callable[[T], None]] = {}
        # One reentrant lock per publisher: every editor on this publisher
        # serializes doc mutation/delivery on it, so interval-driven (timer
        # thread) flushes can never interleave with local edits or with each
        # other — and a single shared lock cannot deadlock the way
        # per-editor locks would (delivery happens inside a flush).
        self.lock = threading.RLock()

    def subscribe(self, key: str, callback: Callable[[T], None]) -> None:
        if key in self._subscribers:
            raise ValueError(f"Subscriber already exists: {key}")
        self._subscribers[key] = callback

    def unsubscribe(self, key: str) -> None:
        if key not in self._subscribers:
            raise ValueError(f"Subscriber not found: {key}")
        del self._subscribers[key]

    def publish(self, sender: str, update: T) -> None:
        # Delivered vs dropped/duplicated/reordered/held-back: the chaos
        # outcomes mirror from FaultPlan._stat (faults.pubsub_deliver.*);
        # this site counts what actually reached callbacks.
        if not telemetry.enabled:
            # Disabled fast path: the untraced loop, one attr check paid.
            for key, callback in list(self._subscribers.items()):
                if key == sender:
                    continue
                for delivered in faults.filter_stream(
                    "pubsub_deliver", [update], stream=key
                ):
                    faults.fire("pubsub_deliver")
                    callback(delivered)
            return
        # Traced path: one causal lane per publish, a step per delivery
        # (the subscriber callbacks run with the lane scoped onto this
        # thread, so their ingest seams join it), terminated when the
        # fan-out completes.  e2e.publish_to_delivered is fed per delivery
        # — the reorder/holdback chaos makes per-receiver latency the
        # interesting number.
        telemetry.counter("pubsub.published")
        ctx = telemetry.flow("pubsub.publish", sender=sender)
        with telemetry.span("pubsub.publish", sender=sender):
            telemetry.flow_point(ctx)
            try:
                for key, callback in list(self._subscribers.items()):
                    if key == sender:
                        continue
                    # Per-subscriber stream: drop/dup/reorder decisions (and
                    # the holdback buffer) are independent per receiver, like
                    # real per-link network chaos.
                    for delivered in faults.filter_stream(
                        "pubsub_deliver", [update], stream=key
                    ):
                        faults.fire("pubsub_deliver")
                        telemetry.counter("pubsub.delivered")
                        with telemetry.span("pubsub.deliver", subscriber=key):
                            telemetry.flow_point(ctx, subscriber=key)
                            with telemetry.flowing((ctx,)):
                                callback(delivered)
                        telemetry.observe(
                            "e2e.publish_to_delivered",
                            telemetry.flow_elapsed_s(ctx),
                        )
            finally:
                # The lane finishes even when a subscriber raises — an
                # unterminated flow would read as a lost change.
                telemetry.flow_point(ctx, terminal=True)
