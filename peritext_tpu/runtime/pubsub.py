"""In-process publish/subscribe fan-out.

Reference: /root/reference/src/pubsub.ts:1-26 (Publisher).  ``publish`` fans an
update out to every subscriber except the sender.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, TypeVar

T = TypeVar("T")


class Publisher(Generic[T]):
    def __init__(self) -> None:
        self._subscribers: Dict[str, Callable[[T], None]] = {}
        # One reentrant lock per publisher: every editor on this publisher
        # serializes doc mutation/delivery on it, so interval-driven (timer
        # thread) flushes can never interleave with local edits or with each
        # other — and a single shared lock cannot deadlock the way
        # per-editor locks would (delivery happens inside a flush).
        self.lock = threading.RLock()

    def subscribe(self, key: str, callback: Callable[[T], None]) -> None:
        if key in self._subscribers:
            raise ValueError(f"Subscriber already exists: {key}")
        self._subscribers[key] = callback

    def unsubscribe(self, key: str) -> None:
        if key not in self._subscribers:
            raise ValueError(f"Subscriber not found: {key}")
        del self._subscribers[key]

    def publish(self, sender: str, update: T) -> None:
        for key, callback in list(self._subscribers.items()):
            if key == sender:
                continue
            callback(update)
