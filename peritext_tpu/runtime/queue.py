"""Outbound change batching queue.

Reference: /root/reference/src/changeQueue.ts:6-52 (ChangeQueue).  Batches
locally generated changes and flushes them through a handler — the host->device
staging-buffer analog in the TPU engine, and the network-batching analog in
replication.  The reference flushes on a 10ms browser timer (tunable to
simulate latency); here the timer is an optional daemon thread, and manual
``flush()`` covers the demo-style "manual sync button" mode
(reference index.ts:119-128).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


class ChangeQueue:
    def __init__(
        self,
        handle_flush: Callable[[List[Any]], None],
        interval: float = 0.01,
        flush_lock: Optional["threading.RLock"] = None,
    ) -> None:
        self._changes: List[Any] = []
        self._handle_flush = handle_flush
        self._interval = interval
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        # Held across pop+handle so two concurrent flushes (timer thread vs
        # a manual sync) cannot publish one actor's changes out of seq
        # order.  Callers pass a shared reentrant lock (the Editor passes
        # its publisher's); default is a private one.
        self._flush_lock = flush_lock if flush_lock is not None else threading.RLock()

    def enqueue(self, *changes: Any) -> None:
        with self._lock:
            self._changes.extend(changes)

    def flush(self) -> None:
        with self._flush_lock:
            with self._lock:
                changes, self._changes = self._changes, []
            self._handle_flush(changes)

    def _tick(self) -> None:
        self.flush()
        with self._lock:
            if self._timer is not None:
                self._timer = threading.Timer(self._interval, self._tick)
                self._timer.daemon = True
                self._timer.start()

    def start(self) -> None:
        with self._lock:
            if self._timer is not None:
                return
            self._timer = threading.Timer(self._interval, self._tick)
            self._timer.daemon = True
            self._timer.start()

    def drop(self) -> None:
        """Stop the timer (go manual-sync).  Reference changeQueue.ts:47-51."""
        with self._lock:
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()

    def __len__(self) -> int:
        with self._lock:
            return len(self._changes)
