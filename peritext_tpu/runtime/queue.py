"""Outbound change batching queue.

Reference: /root/reference/src/changeQueue.ts:6-52 (ChangeQueue).  Batches
locally generated changes and flushes them through a handler — the host->device
staging-buffer analog in the TPU engine, and the network-batching analog in
replication.  The reference flushes on a 10ms browser timer (tunable to
simulate latency); here the timer is an optional daemon thread, and manual
``flush()`` covers the demo-style "manual sync button" mode
(reference index.ts:119-128).

Robustness contract: a flush whose handler raises (or is failed by the
``queue_flush`` fault site) re-enqueues the popped batch at the *front*, so
no change is ever lost and a later flush republishes in original order.  The
timer lifecycle is epoch-guarded: ``drop()`` during an in-flight tick cannot
race a subsequent ``start()`` into leaking a second timer chain.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Callable, List, Optional

from peritext_tpu.runtime import faults
from peritext_tpu.runtime import telemetry

_log = logging.getLogger(__name__)
_queue_ids = itertools.count()


class ChangeQueue:
    def __init__(
        self,
        handle_flush: Callable[[List[Any]], None],
        interval: float = 0.01,
        flush_lock: Optional["threading.RLock"] = None,
        name: Optional[str] = None,
    ) -> None:
        # Chaos stream key: each queue gets its own drop/dup/reorder stream
        # (and holdback buffer) so one queue's held-back changes can never
        # surface through another queue's handler.  Deterministic as long as
        # queue construction order is (pass ``name`` to pin it exactly).
        self._name = name if name is not None else f"queue-{next(_queue_ids)}"
        self._changes: List[Any] = []
        self._handle_flush = handle_flush
        self._interval = interval
        self._timer: Optional[threading.Timer] = None
        # Timer-chain epoch: every start()/drop() bumps it, and a tick only
        # re-arms if its epoch is still current.  Without this, a drop()
        # racing an in-flight tick followed by a fresh start() could leave
        # BOTH the new chain's timer and the old tick's re-arm alive — two
        # timer chains flushing forever.
        self._epoch = 0
        self._lock = threading.Lock()
        # Held across pop+handle so two concurrent flushes (timer thread vs
        # a manual sync) cannot publish one actor's changes out of seq
        # order.  Callers pass a shared reentrant lock (the Editor passes
        # its publisher's); default is a private one.
        self._flush_lock = flush_lock if flush_lock is not None else threading.RLock()

    def enqueue(self, *changes: Any) -> None:
        with self._lock:
            self._changes.extend(changes)
            depth = len(self._changes)
        # High-water mark at enqueue time, not just flush time: depth built
        # up between flushes (a wedged handler) must be visible.
        if telemetry.enabled:
            telemetry.gauge_max("queue.depth_max", depth)

    def flush(self) -> None:
        with self._flush_lock:
            with self._lock:
                changes, self._changes = self._changes, []
            # Depth/latency telemetry only for non-empty flushes — idle
            # 10ms timer ticks would otherwise drown the histograms — and
            # only on SUCCESS, so `queue.flush_depth.count ==
            # queue.flushes` holds even under injected flush failures
            # (failed attempts show up as queue.reenqueues instead, and
            # the re-flushed batch counts once when it finally lands).
            record = telemetry.enabled and bool(changes)
            if record:
                depth = len(changes)
                telemetry.gauge_max("queue.depth_max", depth)
                t0 = time.perf_counter()
            try:
                if changes:
                    # Chaos plane: fail/wedge the flush.  Only fired for
                    # non-empty batches so counted schedules aren't consumed
                    # by idle timer ticks.
                    faults.fire("queue_flush")
                # drop/dup/reorder the batch.  Runs for EMPTY batches too:
                # a held-back (reordered) change must be able to re-emerge
                # on a later idle tick, not stay stranded once the editor
                # goes quiet.
                changes = faults.filter_stream(
                    "queue_flush", changes, stream=self._name
                )
                self._handle_flush(changes)
                if record:
                    telemetry.counter("queue.flushes")
                    telemetry.observe("queue.flush_depth", depth)
                    telemetry.observe(
                        "queue.flush_seconds", time.perf_counter() - t0
                    )
            except BaseException:
                # A failed flush must not lose the batch: put the surviving
                # changes back at the front so a later flush retries them
                # ahead of anything enqueued meanwhile.
                with self._lock:
                    self._changes[:0] = changes
                if record:
                    telemetry.counter("queue.reenqueues", len(changes))
                raise

    def _tick(self, epoch: int) -> None:
        try:
            self.flush()
        except Exception:
            # A failing flush (handler error, injected fault) must not kill
            # the timer chain: the batch was re-enqueued by flush(), so the
            # next tick retries it.  Log it — the timer thread has no caller
            # to propagate to.
            _log.warning("change-queue flush failed; will retry", exc_info=True)
        finally:
            with self._lock:
                if self._timer is not None and epoch == self._epoch:
                    self._arm_locked()

    def _arm_locked(self) -> None:
        timer = threading.Timer(self._interval, self._tick, args=(self._epoch,))
        timer.daemon = True
        self._timer = timer
        timer.start()

    def start(self) -> None:
        with self._lock:
            if self._timer is not None:
                return  # already running: never arm a second chain
            self._epoch += 1
            self._arm_locked()

    def drop(self) -> None:
        """Stop the timer (go manual-sync).  Reference changeQueue.ts:47-51."""
        with self._lock:
            timer, self._timer = self._timer, None
            self._epoch += 1  # invalidate any in-flight tick's re-arm
        if timer is not None:
            timer.cancel()

    def __len__(self) -> int:
        with self._lock:
            return len(self._changes)
