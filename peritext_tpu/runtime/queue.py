"""Outbound change batching queue.

Reference: /root/reference/src/changeQueue.ts:6-52 (ChangeQueue).  Batches
locally generated changes and flushes them through a handler — the host->device
staging-buffer analog in the TPU engine, and the network-batching analog in
replication.  The reference flushes on a 10ms browser timer (tunable to
simulate latency); here the timer is an optional daemon thread, and manual
``flush()`` covers the demo-style "manual sync button" mode
(reference index.ts:119-128).

Robustness contract: a flush whose handler raises (or is failed by the
``queue_flush`` fault site) re-enqueues the popped batch at the *front*, so
no change is ever lost and a later flush republishes in original order.  The
timer lifecycle is epoch-guarded: ``drop()`` during an in-flight tick cannot
race a subsequent ``start()`` into leaking a second timer chain.

Admission control (the health plane's memory bound): an unbounded queue lets
producers pile up work a sick backend cannot drain — under a wedged relay the
10ms timer re-fails forever while enqueues keep growing the list.  A bound
(``bound=``, default ``PERITEXT_QUEUE_BOUND``; 0 = unbounded) caps the
pending depth, with a pluggable backpressure ``policy``
(``PERITEXT_QUEUE_POLICY``):

- ``block`` (default): ``enqueue`` waits until a flush frees space (an
  optional ``block_timeout`` raises :class:`QueueFullError` instead of
  waiting forever).  Lossless; producers feel the backpressure directly.
- ``coalesce``: per-actor run coalescing — at the bound, *adjacent* pending
  changes from the same actor collapse into one queue entry (the bound
  counts entries), so the single-author editor case (one queue per actor —
  the repo's idiom) stays O(1) entries under a wedged backend while exact
  global FIFO order is preserved.  Lossless; incompressible interleavings
  of distinct actors overflow the bound softly (counted).
- ``shed``: oldest changes are dropped to make room, with telemetry
  (``queue.shed``) and a warning — bounded memory at the cost of relying on
  anti-entropy (the durable change log) to redeliver what was shed.

Every policy decision lands in the telemetry registry: ``queue.blocked`` /
``queue.block_seconds``, ``queue.coalesced`` / ``queue.coalesce_overflow``,
``queue.shed``, alongside the existing depth/flush metrics.
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, List, Optional

from peritext_tpu.runtime import faults
from peritext_tpu.runtime import telemetry

_log = logging.getLogger(__name__)
_queue_ids = itertools.count()

# THE backpressure policy vocabulary, shared by every admission-control
# surface in the runtime: ChangeQueue bounds (this module) and the serving
# plane's per-session lanes (runtime/serve.py) accept exactly these names,
# with the same semantics — block waits, coalesce merges losslessly at the
# bound, shed drops oldest with telemetry.
POLICIES = ("block", "coalesce", "shed")
_POLICIES = POLICIES


class QueueFullError(RuntimeError):
    """A blocking enqueue exceeded its ``block_timeout`` with the queue
    still at its bound (the backend is not draining)."""


class _Run:
    """A coalesced run of adjacent changes from one actor (one queue entry)."""

    __slots__ = ("actor", "changes")

    def __init__(self, actor: Any, changes: List[Any]) -> None:
        self.actor = actor
        self.changes = changes


def _actor_of(entry: Any) -> Any:
    if isinstance(entry, _Run):
        return entry.actor
    if isinstance(entry, dict):
        return entry.get("actor")
    return None


def _flatten(entries) -> List[Any]:
    if not any(isinstance(e, _Run) for e in entries):
        return list(entries)
    out: List[Any] = []
    for e in entries:
        if isinstance(e, _Run):
            out.extend(e.changes)
        else:
            out.append(e)
    return out


def _entry_size(entry: Any) -> int:
    return len(entry.changes) if isinstance(entry, _Run) else 1


class ChangeQueue:
    def __init__(
        self,
        handle_flush: Callable[[List[Any]], None],
        interval: float = 0.01,
        flush_lock: Optional["threading.RLock"] = None,
        name: Optional[str] = None,
        bound: Optional[int] = None,
        policy: Optional[str] = None,
        block_timeout: Optional[float] = None,
    ) -> None:
        # Chaos stream key: each queue gets its own drop/dup/reorder stream
        # (and holdback buffer) so one queue's held-back changes can never
        # surface through another queue's handler.  Deterministic as long as
        # queue construction order is (pass ``name`` to pin it exactly).
        self._name = name if name is not None else f"queue-{next(_queue_ids)}"
        # Entries (plain changes or coalesced _Runs) + an incrementally
        # tracked flattened depth, so admission never rescans the queue.
        self._changes: Deque[Any] = deque()
        self._depth = 0
        # Causal-flow lanes for the pending entries: one TraceContext per
        # enqueue call (batch granularity, like every instrumented site),
        # popped wholesale by the flush that drains them.  Only populated
        # while telemetry is enabled.
        self._flows: List[Any] = []
        self._handle_flush = handle_flush
        self._interval = interval
        self._timer: Optional[threading.Timer] = None
        # Timer-chain epoch: every start()/drop() bumps it, and a tick only
        # re-arms if its epoch is still current.  Without this, a drop()
        # racing an in-flight tick followed by a fresh start() could leave
        # BOTH the new chain's timer and the old tick's re-arm alive — two
        # timer chains flushing forever.
        self._epoch = 0
        self._lock = threading.Lock()
        # Signaled whenever a flush pops the queue; blocking enqueues wait
        # on it.  Shares the state lock, so waiters observe a consistent
        # depth.
        self._drained = threading.Condition(self._lock)
        if bound is None:
            bound = int(os.environ.get("PERITEXT_QUEUE_BOUND", "0") or 0)
        self._bound = max(0, bound)
        if policy is None:
            policy = os.environ.get("PERITEXT_QUEUE_POLICY", "block")
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown queue policy {policy!r}; known policies: "
                f"{', '.join(_POLICIES)}"
            )
        self._policy = policy
        self._block_timeout = block_timeout
        # Held across pop+handle so two concurrent flushes (timer thread vs
        # a manual sync) cannot publish one actor's changes out of seq
        # order.  Callers pass a shared reentrant lock (the Editor passes
        # its publisher's); default is a private one.
        self._flush_lock = flush_lock if flush_lock is not None else threading.RLock()

    # -- admission -----------------------------------------------------------

    def enqueue(self, *changes: Any) -> None:
        """Admit a batch under the bound/policy.  Atomic per call: either
        every change is admitted (one lock hold, FIFO-contiguous) or — the
        block policy's timeout — none is, so callers can safely retry a
        QueueFullError without duplicating a half-admitted prefix."""
        if telemetry.enabled and changes:
            self._enqueue_traced(changes)
            return
        with self._drained:
            self._admit_locked(changes)

    def _admit_locked(self, changes: tuple) -> None:
        if not self._bound:
            self._changes.extend(changes)
            self._depth += len(changes)
        elif self._policy == "block":
            self._admit_blocking_locked(changes)
        elif self._policy == "shed":
            self._admit_shedding_locked(changes)
        else:
            for change in changes:
                self._admit_coalescing_locked(change)

    def _enqueue_traced(self, changes: tuple) -> None:
        """Traced admission.  The lane's start event is emitted BEFORE the
        context is published to the flush side, and publication happens in
        the SAME lock hold that admits the changes — so a timer flush
        racing this enqueue can neither drain the batch without its lane
        nor emit the lane's steps ahead of its start."""
        ctx = telemetry.flow("queue.change", queue=self._name, changes=len(changes))
        with telemetry.span("queue.enqueue", changes=len(changes)):
            telemetry.flow_point(ctx)
            try:
                with self._drained:
                    self._admit_locked(changes)
                    self._flows.append(ctx)
                    depth = self._depth
            except BaseException:
                # Nothing was admitted (block-timeout): the lane ends here
                # instead of dangling as an orphan start.
                telemetry.flow_point(ctx, terminal=True, outcome="rejected")
                raise
        # High-water mark at enqueue time, not just flush time: depth built
        # up between flushes (a wedged handler) must be visible.
        telemetry.gauge_max("queue.depth_max", depth)

    def _admit_blocking_locked(self, changes: tuple) -> None:
        """Wait until the whole batch fits (or the queue is empty — a batch
        larger than the bound must not deadlock; it overflows softly once
        it is the only occupant).  On timeout, nothing was admitted."""
        n = len(changes)
        t0: Optional[float] = None
        deadline = (
            None
            if self._block_timeout is None
            else time.monotonic() + self._block_timeout
        )
        while self._depth > 0 and self._depth + n > self._bound:
            if t0 is None:
                t0 = time.perf_counter()
                if telemetry.enabled:
                    telemetry.counter("queue.blocked")
            if deadline is None:
                self._drained.wait()
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._drained.wait(remaining):
                    if telemetry.enabled:
                        telemetry.observe(
                            "queue.block_seconds", time.perf_counter() - t0
                        )
                    raise QueueFullError(
                        f"queue {self._name} still at bound "
                        f"{self._bound} after {self._block_timeout}s"
                    )
        if t0 is not None and telemetry.enabled:
            telemetry.observe("queue.block_seconds", time.perf_counter() - t0)
        self._changes.extend(changes)
        self._depth += n

    def _admit_shedding_locked(self, changes: tuple) -> None:
        # Causal lanes are per enqueue CALL, so shedding individual entries
        # cannot unmap "their" lane; shed batches' lanes terminate at the
        # next flush (their e2e then includes shed residency — an accepted
        # approximation of an explicitly lossy, telemetry-flagged policy).
        self._changes.extend(changes)
        self._depth += len(changes)
        shed = 0
        while self._depth > self._bound:
            shed_n = _entry_size(self._changes.popleft())
            self._depth -= shed_n
            shed += shed_n
        if shed:
            # The entry bound just dropped data, so the lane list must not
            # keep growing either (CLAUDE.md: "Memory stays flat under a
            # wedged backend").  Terminate the oldest lanes down to the
            # bound — their changes are the ones most likely shed — with an
            # explicit "shed" outcome and no e2e observation.  We are
            # inside the traced enqueue's span (or emitting is a no-op
            # untraced), so the finish events stay bound.
            while len(self._flows) > self._bound:
                telemetry.flow_point(
                    self._flows.pop(0), terminal=True, outcome="shed"
                )
            if telemetry.enabled:
                telemetry.counter("queue.shed", shed)
            _log.warning(
                "change queue %s over bound %d: shed %d oldest "
                "change(s) (redelivery relies on anti-entropy)",
                self._name,
                self._bound,
                shed,
            )

    def _compact_runs_locked(self) -> int:
        """Merge adjacent same-actor entries into runs; returns the number
        of changes absorbed.  Exact global FIFO is preserved: a run sits at
        its first change's position and flattens back in order."""
        merged = 0
        out: List[Any] = []
        for e in self._changes:
            actor = _actor_of(e)
            prev = out[-1] if out else None
            if actor is not None and prev is not None and _actor_of(prev) == actor:
                if not isinstance(prev, _Run):
                    out[-1] = prev = _Run(actor, [prev])
                if isinstance(e, _Run):
                    prev.changes.extend(e.changes)
                    merged += len(e.changes)
                else:
                    prev.changes.append(e)
                    merged += 1
            else:
                out.append(e)
        self._changes = deque(out)
        return merged

    def _admit_coalescing_locked(self, change: Any) -> None:
        self._depth += 1  # coalescing is lossless: depth always grows
        # The bound caps ENTRIES; runs keep the change count exact.
        if len(self._changes) < self._bound:
            self._changes.append(change)
            return
        merged = self._compact_runs_locked()
        actor = _actor_of(change)
        prev = self._changes[-1] if self._changes else None
        if actor is not None and prev is not None and _actor_of(prev) == actor:
            if not isinstance(prev, _Run):
                self._changes[-1] = prev = _Run(actor, [prev])
            prev.changes.append(change)
            merged += 1
        elif len(self._changes) < self._bound:
            self._changes.append(change)
        else:
            # Incompressible (distinct actors interleaved at the bound):
            # keep the change anyway — coalesce bounds entries, never
            # sheds data.
            self._changes.append(change)
            if telemetry.enabled:
                telemetry.counter("queue.coalesce_overflow")
        if merged and telemetry.enabled:
            telemetry.counter("queue.coalesced", merged)

    # -- flushing ------------------------------------------------------------

    def flush(self) -> None:
        with self._flush_lock:
            with self._drained:
                entries, self._changes = self._changes, deque()
                flows, self._flows = self._flows, []
                self._depth = 0
                self._drained.notify_all()
            changes = _flatten(entries)
            # Depth/latency telemetry only for non-empty flushes — idle
            # 10ms timer ticks would otherwise drown the histograms — and
            # only on SUCCESS, so `queue.flush_depth.count ==
            # queue.flushes` holds even under injected flush failures
            # (failed attempts show up as queue.reenqueues instead, and
            # the re-flushed batch counts once when it finally lands).
            record = telemetry.enabled and bool(changes)
            if record:
                depth = len(changes)
                telemetry.gauge_max("queue.depth_max", depth)
                t0 = time.perf_counter()
            try:
                if changes:
                    # Chaos plane: fail/wedge the flush.  Only fired for
                    # non-empty batches so counted schedules aren't consumed
                    # by idle timer ticks.
                    faults.fire("queue_flush")
                # drop/dup/reorder the batch.  Runs for EMPTY batches too:
                # a held-back (reordered) change must be able to re-emerge
                # on a later idle tick, not stay stranded once the editor
                # goes quiet.
                changes = faults.filter_stream(
                    "queue_flush", changes, stream=self._name
                )
                if record:
                    # The flush span is the lanes' hand-off slice: every
                    # pending lane steps through it, the handler runs with
                    # the lanes scoped onto this thread (so ingest seams
                    # join them), and handler success is the terminal seam
                    # — it feeds e2e.enqueue_to_applied and finishes the
                    # flow.
                    with telemetry.span("queue.flush", depth=depth):
                        for ctx in flows:
                            telemetry.flow_point(ctx)
                        with telemetry.flowing(flows):
                            self._handle_flush(changes)
                        for ctx in flows:
                            if ctx is not None:
                                telemetry.observe(
                                    "e2e.enqueue_to_applied",
                                    telemetry.flow_elapsed_s(ctx),
                                )
                                telemetry.flow_point(ctx, terminal=True)
                    telemetry.record(
                        "queue.flush", outcome="applied", depth=depth
                    )
                    telemetry.counter("queue.flushes")
                    telemetry.observe("queue.flush_depth", depth)
                    telemetry.observe(
                        "queue.flush_seconds", time.perf_counter() - t0
                    )
                else:
                    self._handle_flush(changes)
                    if flows and telemetry.enabled:
                        # Lanes popped with no recordable batch (every
                        # entry was shed, or telemetry toggled between
                        # enqueue and flush): terminate them without an
                        # e2e observation — a dropped lane must still end,
                        # never dangle as an orphan start.
                        with telemetry.span("queue.flush_dropped", flows=len(flows)):
                            for ctx in flows:
                                telemetry.flow_point(
                                    ctx, terminal=True, outcome="dropped"
                                )
            except BaseException:
                # A failed flush must not lose the batch: put the surviving
                # changes back at the front so a later flush retries them
                # ahead of anything enqueued meanwhile (changes an enqueue
                # raced in DURING this failed flush sit behind the popped
                # batch — FIFO holds across the failure; pinned by
                # tests/test_faults.py).  Deliberately past the bound: the
                # batch was admitted once and must not be re-judged.
                # The lanes ride along: the retry flush that finally lands
                # is what finishes them.
                with self._lock:
                    self._changes.extendleft(reversed(changes))
                    self._depth += len(changes)
                    self._flows[:0] = flows
                if record:
                    telemetry.counter("queue.reenqueues", len(changes))
                    telemetry.record(
                        "queue.flush", outcome="error", depth=depth
                    )
                raise

    def _tick(self, epoch: int) -> None:
        try:
            self.flush()
        except Exception:
            # A failing flush (handler error, injected fault) must not kill
            # the timer chain: the batch was re-enqueued by flush(), so the
            # next tick retries it.  Log it — the timer thread has no caller
            # to propagate to.
            _log.warning("change-queue flush failed; will retry", exc_info=True)
        finally:
            with self._lock:
                if self._timer is not None and epoch == self._epoch:
                    self._arm_locked()

    def _arm_locked(self) -> None:
        timer = threading.Timer(self._interval, self._tick, args=(self._epoch,))
        timer.daemon = True
        self._timer = timer
        timer.start()

    def start(self) -> None:
        with self._lock:
            if self._timer is not None:
                return  # already running: never arm a second chain
            self._epoch += 1
            self._arm_locked()

    def drop(self) -> None:
        """Stop the timer (go manual-sync).  Reference changeQueue.ts:47-51."""
        with self._lock:
            timer, self._timer = self._timer, None
            self._epoch += 1  # invalidate any in-flight tick's re-arm
        if timer is not None:
            timer.cancel()

    def entries(self) -> int:
        """Pending queue entries (coalesced runs count once — the quantity
        the ``coalesce`` policy bounds)."""
        with self._lock:
            return len(self._changes)

    def __len__(self) -> int:
        """Pending changes (coalesced runs count their members)."""
        with self._lock:
            return self._depth
