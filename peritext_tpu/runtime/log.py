"""Append-only per-actor change logs — the durable replication substrate.

Reference: the fuzzer's ``SharedHistory`` (test/fuzz.ts:160-163) and the
vector-clock diff ``getMissingChanges`` (test/merge.ts:25-38).  A change log
is the CRDT's only durable state: any replica is reconstructible by replaying
logs through ``apply_change`` (this is exactly how the reference's failure
traces work — they serialize ``queues``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping

Change = Dict[str, Any]
Clock = Mapping[str, int]


class ChangeLog:
    """Per-actor append-only sequences of changes, indexed by seq."""

    def __init__(self) -> None:
        self._queues: Dict[str, List[Change]] = {}

    def append(self, change: Change) -> None:
        queue = self._queues.setdefault(change["actor"], [])
        expected = len(queue) + 1
        if change["seq"] != expected:
            raise ValueError(
                f"Log gap for {change['actor']}: expected seq {expected}, got {change['seq']}"
            )
        queue.append(change)

    def record(self, change: Change) -> None:
        """Append if this change extends the log; ignore if already present."""
        queue = self._queues.setdefault(change["actor"], [])
        if change["seq"] == len(queue) + 1:
            queue.append(change)
        elif change["seq"] > len(queue) + 1:
            raise ValueError(
                f"Log gap for {change['actor']}: have {len(queue)}, got seq {change['seq']}"
            )

    def clock(self) -> Dict[str, int]:
        return {actor: len(queue) for actor, queue in self._queues.items()}

    def changes_for(self, actor: str) -> List[Change]:
        return list(self._queues.get(actor, []))

    def missing_changes(self, source_clock: Clock, target_clock: Clock) -> List[Change]:
        """Changes the source has seen that the target hasn't.

        Reference test/merge.ts:25-38 (getMissingChanges): vector-clock diff,
        pulling from the per-actor queues.
        """
        changes: List[Change] = []
        for actor, count in source_clock.items():
            have = target_clock.get(actor)
            if have is None:
                changes.extend(self._queues.get(actor, [])[:count])
            elif have < count:
                changes.extend(self._queues.get(actor, [])[have:count])
        return changes

    def all_changes(self) -> List[Change]:
        out: List[Change] = []
        for queue in self._queues.values():
            out.extend(queue)
        return out

    @property
    def actors(self) -> List[str]:
        return list(self._queues)
