"""Append-only per-actor change logs — the durable replication substrate.

Reference: the fuzzer's ``SharedHistory`` (test/fuzz.ts:160-163) and the
vector-clock diff ``getMissingChanges`` (test/merge.ts:25-38).  A change log
is the CRDT's only durable state: any replica is reconstructible by replaying
logs through ``apply_change`` (this is exactly how the reference's failure
traces work — they serialize ``queues``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping

from peritext_tpu.runtime import faults
from peritext_tpu.runtime import telemetry

Change = Dict[str, Any]
Clock = Mapping[str, int]


class ChangeLog:
    """Per-actor append-only sequences of changes, indexed by seq."""

    def __init__(self) -> None:
        self._queues: Dict[str, List[Change]] = {}

    def append(self, change: Change) -> None:
        # Durability chokepoint: an injected ``log_append`` failure models a
        # lost write — it raises *before* any mutation, so the log never
        # holds a half-recorded change.
        faults.fire("log_append")
        if telemetry.enabled:
            telemetry.counter("log.appends")
        queue = self._queues.setdefault(change["actor"], [])
        expected = len(queue) + 1
        if change["seq"] != expected:
            raise ValueError(
                f"Log gap for {change['actor']}: expected seq {expected}, got {change['seq']}"
            )
        queue.append(change)

    def record(self, change: Change) -> None:
        """Append if this change extends the log; ignore if already present.

        An already-covered seq must match the stored change byte-for-byte:
        a mismatch means a forked actor history or a corrupted log, which
        must surface rather than silently drop.
        """
        faults.fire("log_append")
        if telemetry.enabled:
            telemetry.counter("log.appends")
        if change["seq"] < 1:
            # Validate before touching the log: a rejected record must not
            # create a phantom actor entry in clock()/missing_changes.
            raise ValueError(
                f"Invalid seq {change['seq']} for {change['actor']}: seqs are 1-based"
            )
        queue = self._queues.setdefault(change["actor"], [])
        if change["seq"] == len(queue) + 1:
            queue.append(change)
        elif change["seq"] > len(queue) + 1:
            raise ValueError(
                f"Log gap for {change['actor']}: have {len(queue)}, got seq {change['seq']}"
            )
        else:
            stored = queue[change["seq"] - 1]
            if stored != change:
                raise ValueError(
                    f"Log conflict for {change['actor']} seq {change['seq']}: "
                    "incoming change differs from the stored one (forked history?)"
                )

    def clock(self) -> Dict[str, int]:
        return {actor: len(queue) for actor, queue in self._queues.items()}

    def changes_for(self, actor: str) -> List[Change]:
        return list(self._queues.get(actor, []))

    def missing_changes(self, source_clock: Clock, target_clock: Clock) -> List[Change]:
        """Changes the source has seen that the target hasn't.

        Reference test/merge.ts:25-38 (getMissingChanges): vector-clock diff,
        pulling from the per-actor queues.
        """
        changes: List[Change] = []
        for actor, count in source_clock.items():
            have = target_clock.get(actor)
            if have is None:
                changes.extend(self._queues.get(actor, [])[:count])
            elif have < count:
                changes.extend(self._queues.get(actor, [])[have:count])
        return changes

    def all_changes(self) -> List[Change]:
        out: List[Change] = []
        for queue in self._queues.values():
            out.extend(queue)
        return out

    @property
    def actors(self) -> List[str]:
        return list(self._queues)

    # -- binary persistence (native codec) ----------------------------------

    def to_bytes(self) -> bytes:
        """Serialize the whole log: columnar-varint op rows (the native
        codec, runtime/native_codec.py) + a JSON envelope for headers,
        intern tables, and structural ops.

        The binary analog of the reference's JSON change format
        (micromerge.ts:60-71, which cites Automerge's binary change format
        as the real-world encoding).
        """
        import json

        import numpy as np

        from peritext_tpu.ids import ActorRegistry
        from peritext_tpu.ops import kernels as K
        from peritext_tpu.ops.encode import AttrRegistry, encode_internal_op
        from peritext_tpu.runtime.native_codec import encode_columns

        actors = ActorRegistry()
        attrs = AttrRegistry()
        rows: List[Any] = []
        obj_table: List[Any] = []
        obj_index: Dict[Any, int] = {}
        obj_col: List[int] = []
        envelope: Dict[str, Any] = {"changes": []}
        for queue in self._queues.values():
            for change in queue:
                header = {k: change[k] for k in ("actor", "seq", "deps", "startOp")}
                ops_meta: List[Any] = []
                for op in change["ops"]:
                    try:
                        row = encode_internal_op(op, actors, attrs)
                    except ValueError:
                        # Host-list op with a value the char plane can't
                        # carry (e.g. a multi-codepoint element in a nested
                        # list — legal in the object model): envelope JSON,
                        # like structural ops.
                        row = None
                    if row is None:
                        ops_meta.append(op)  # structural / unencodable: raw JSON
                    else:
                        ops_meta.append(None)  # device op: row stream
                        rows.append(row)
                        obj = op.get("obj")
                        if obj not in obj_index:
                            obj_index[obj] = len(obj_table)
                            obj_table.append(obj)
                        obj_col.append(obj_index[obj])
                envelope["changes"].append({"header": header, "ops": ops_meta})
        matrix = (
            np.concatenate(
                [np.stack(rows).T, np.asarray(obj_col, np.int32)[None, :]]
            )
            if rows
            else np.zeros((K.OP_FIELDS + 1, 0), np.int32)
        )
        payload = encode_columns(matrix)
        envelope["obj_table"] = obj_table
        envelope["actors"] = actors.actors
        envelope["attrs"] = attrs.values
        envelope["n_rows"] = matrix.shape[1]
        head = json.dumps(envelope).encode()
        return (
            len(head).to_bytes(8, "little") + head + payload
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "ChangeLog":
        import json

        from peritext_tpu.ids import ActorRegistry
        from peritext_tpu.ops import kernels as K
        from peritext_tpu.ops.encode import AttrRegistry, decode_internal_op
        from peritext_tpu.runtime.native_codec import decode_columns

        head_len = int.from_bytes(data[:8], "little")
        envelope = json.loads(data[8 : 8 + head_len])
        matrix = decode_columns(
            data[8 + head_len :], K.OP_FIELDS + 1, envelope["n_rows"]
        )
        actors = ActorRegistry()
        for actor in envelope["actors"]:
            actors.intern(actor)
        attrs = AttrRegistry()
        for attr in envelope["attrs"]:
            attrs.intern(attr)

        log = cls()
        row_i = 0
        for entry in envelope["changes"]:
            ops = []
            for op_meta in entry["ops"]:
                if op_meta is not None:
                    ops.append(op_meta)
                else:
                    obj = envelope["obj_table"][int(matrix[K.OP_FIELDS, row_i])]
                    ops.append(
                        decode_internal_op(matrix[:, row_i], actors, attrs, obj)
                    )
                    row_i += 1
            log.record({**entry["header"], "ops": ops})
        return log
