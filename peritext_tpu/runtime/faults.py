"""Process-wide deterministic fault injection (the chaos plane).

Peritext's correctness claim is convergence under arbitrary delivery orders,
duplication, and loss — and the hardware rounds documented in CLAUDE.md show
that on a real relayed TPU the *normal* failure mode is a wedged relay, an
early-returning completion barrier, or mid-run device death.  This module
makes those failures reproducible: a seeded :class:`FaultPlan` holds one
schedule per named **site**, and the runtime fires the sites at its natural
chokepoints:

========================  ====================================================
site                      fired from
========================  ====================================================
``device_launch``         every kernel launch attempt (ops/universe.py,
                          ops/doc.py local generation)
``device_readback``       the host readback barrier — the only honest
                          completion signal on the relay (ops/universe.py
                          strict-commit / per-attempt deadline, ops/doc.py
                          anchor queries)
``pubsub_deliver``        per-subscriber delivery (runtime/pubsub.py)
``queue_flush``           outbound batch flush (runtime/queue.py)
``checkpoint_write``      snapshot save (runtime/checkpoint.py)
``log_append``            durable change-log append (runtime/log.py)
``serve_admit``           serving-plane session admission (runtime/serve.py;
                          ``fail``/``wedge`` hit the submit call,
                          drop/dup/reorder filter the submitted changes)
``shard_migrate``         live session migration between serving shards
                          (runtime/elastic.py; ``fail``/``wedge`` hit every
                          protocol step — drain, export, provision, import,
                          commit — and drop/dup/reorder filter the parked
                          submissions replayed onto the target shard)
``doc_evict``             session eviction to durable checkpoint
                          (runtime/lifecycle.py; ``fail``/``wedge`` hit every
                          protocol step — drain, export, persist, commit —
                          and ``corrupt=N`` truncates the just-written
                          generation npz, the crash-corruption drill)
``doc_hydrate``           cold-session hydration from checkpoint
                          (runtime/lifecycle.py; ``fail``/``wedge`` hit every
                          protocol step — provision, load, import, replay,
                          commit — and drop/dup/reorder filter the parked
                          deliveries replayed at commit)
========================  ====================================================

Schedules per site (all deterministic given the plan seed and call order):

- ``fail=N`` — the next N fires raise :class:`FaultError`.
- ``wedge=TxN`` — the next N fires sleep T seconds first (default N=1);
  models the wedged relay (pairs with ``PERITEXT_LAUNCH_TIMEOUT``).
- ``drop=P`` / ``dup=P`` / ``reorder=P`` — per-message probabilities for
  stream sites (:func:`filter_stream`); reordered messages are held back and
  re-emerge on later calls for the same stream.
- ``corrupt=N`` — consumed by the site's writer (checkpoint save truncates
  the written npz), for crash-corruption drills.

Enable via ``PERITEXT_FAULTS=<spec>`` or programmatically::

    PERITEXT_FAULTS="seed=7;device_launch:fail=2;pubsub_deliver:drop=0.3,dup=0.1"

    with faults.injected("device_launch:fail=1"):
        uni.apply_changes(...)   # first launch attempt fails, retry succeeds

Sites fire as no-ops when no plan is active, so production paths pay one
module-attribute check.  Counters live on the plan (``plan.stats``), so chaos
tests can assert exactly how many faults actually landed.
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from peritext_tpu.runtime import telemetry

KNOWN_SITES = (
    "device_launch",
    "device_readback",
    "pubsub_deliver",
    "queue_flush",
    "checkpoint_write",
    "log_append",
    "serve_admit",
    "shard_migrate",
    "doc_evict",
    "doc_hydrate",
)

_STAT_KEYS = ("fired", "failed", "wedged", "dropped", "duplicated", "reordered", "corrupted")


class FaultError(RuntimeError):
    """An injected failure (always classified as transient/retryable)."""

    def __init__(self, site: str, message: Optional[str] = None):
        super().__init__(message or f"injected fault at site {site!r}")
        self.site = site


def retryable(exc: BaseException) -> bool:
    """Transient-failure classification shared by every retry policy in the
    runtime: injected faults, backend/runtime errors (XlaRuntimeError
    subclasses RuntimeError), OS-level failures and deadline misses are
    worth retrying; semantic errors (ValueError/TypeError/KeyError — bad
    ops, gate violations) and NotImplementedError are permanent."""
    if isinstance(exc, FaultError):
        return True
    if isinstance(exc, NotImplementedError):
        return False
    return isinstance(exc, (RuntimeError, OSError, TimeoutError))


class SiteRule:
    """One site's fault schedule (mutable counters, guarded by the plan lock)."""

    __slots__ = ("fail", "wedge_seconds", "wedge", "drop", "dup", "reorder", "corrupt")

    def __init__(self) -> None:
        self.fail = 0  # remaining fires that raise
        self.wedge_seconds = 0.0
        self.wedge = 0  # remaining fires that sleep first
        self.drop = 0.0  # per-message probabilities
        self.dup = 0.0
        self.reorder = 0.0
        self.corrupt = 0  # remaining corrupt-on-write events

    def set_action(self, action: str, value: str) -> None:
        if action == "fail":
            self.fail = int(value)
        elif action == "wedge":
            secs, _, count = value.partition("x")
            self.wedge_seconds = float(secs)
            self.wedge = int(count) if count else 1
        elif action == "drop":
            self.drop = float(value)
        elif action == "dup":
            self.dup = float(value)
        elif action == "reorder":
            self.reorder = float(value)
        elif action == "corrupt":
            self.corrupt = int(value)
        else:
            raise ValueError(f"unknown fault action {action!r}")


class FaultPlan:
    """A seeded set of per-site fault schedules.

    Deterministic: probabilistic decisions come from one ``random.Random``
    per (site, stream) seeded from the plan seed, and counted schedules
    (fail/wedge/corrupt) decrement on each event — the same call sequence
    always injects the same faults.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self._rules: Dict[str, SiteRule] = {}
        self._rngs: Dict[Any, random.Random] = {}
        self._held: Dict[Any, List[Any]] = {}
        self._lock = threading.RLock()
        self.stats: Dict[str, Dict[str, int]] = {}

    # -- construction -------------------------------------------------------

    def site(self, name: str) -> SiteRule:
        if name not in KNOWN_SITES:
            # A typo'd site name would otherwise inject nothing and let a
            # chaos run pass vacuously — fail at plan-construction time.
            raise ValueError(
                f"unknown fault site {name!r}; known sites: {', '.join(KNOWN_SITES)}"
            )
        rule = self._rules.get(name)
        if rule is None:
            rule = self._rules[name] = SiteRule()
        return rule

    def with_site(self, name: str, **actions: Any) -> "FaultPlan":
        """Programmatic spec: ``plan.with_site("device_launch", fail=2)``."""
        rule = self.site(name)
        for action, value in actions.items():
            rule.set_action(action, str(value))
        return self

    @classmethod
    def from_spec(cls, spec: str, seed: Optional[int] = None) -> "FaultPlan":
        """Parse the ``PERITEXT_FAULTS`` grammar.

        ``spec ::= clause (";" clause)*``;  a clause is either ``seed=N`` or
        ``site:action=value[,action=value...]``.
        """
        plan = cls(seed=seed if seed is not None else 0)
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed=") and ":" not in clause:
                if seed is None:
                    plan.seed = int(clause[5:])
                continue
            site_name, sep, actions = clause.partition(":")
            if not sep or not actions:
                raise ValueError(
                    f"bad fault clause {clause!r} (want site:action=value[,...])"
                )
            rule = plan.site(site_name.strip())
            for part in actions.split(","):
                action, sep, value = part.partition("=")
                if not sep:
                    raise ValueError(f"bad fault action {part!r} in clause {clause!r}")
                rule.set_action(action.strip(), value.strip())
        return plan

    # -- bookkeeping ---------------------------------------------------------

    def _stat(self, site: str, key: str, n: int = 1) -> None:
        stats = self.stats.setdefault(site, {k: 0 for k in _STAT_KEYS})
        stats[key] += n
        # Mirror every landed fault into the telemetry registry
        # (``faults.<site>.<key>``): seeded chaos runs become
        # self-describing, and tests assert the two tallies agree exactly
        # (same seed + call order ⇒ same counts on both planes).
        if telemetry.enabled:
            telemetry.counter(f"faults.{site}.{key}", n)

    def _rng(self, site: str, stream: str) -> random.Random:
        key = (site, stream)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = random.Random(f"{self.seed}/{site}/{stream}")
        return rng

    # -- the injection points ------------------------------------------------

    def fire(self, site: str) -> None:
        """Control-point hook: may sleep (wedge) and/or raise (fail)."""
        with self._lock:
            rule = self._rules.get(site)
            if rule is None:
                return
            self._stat(site, "fired")
            wedge = 0.0
            if rule.wedge > 0:
                rule.wedge -= 1
                wedge = rule.wedge_seconds
                self._stat(site, "wedged")
            failing = rule.fail > 0
            if failing:
                rule.fail -= 1
                self._stat(site, "failed")
        # Sleep outside the lock: a wedge must not serialize other sites.
        if wedge:
            time.sleep(wedge)
        if failing:
            raise FaultError(site)

    def take(self, site: str, action: str) -> bool:
        """Consume one counted event of ``action`` (used for ``corrupt``)."""
        with self._lock:
            rule = self._rules.get(site)
            if rule is None:
                return False
            if action == "corrupt" and rule.corrupt > 0:
                rule.corrupt -= 1
                self._stat(site, "corrupted")
                return True
            return False

    def filter_stream(self, site: str, items: Iterable[Any], stream: str = "") -> List[Any]:
        """Apply drop/dup/reorder schedules to a message batch.

        Reordered messages are held back in a per-(site, stream) buffer and
        re-emerge (ahead of newer traffic, coin-flipped per call) on later
        calls for the same stream; :meth:`drain` flushes the leftovers for a
        final fault-free sync.
        """
        items = list(items)
        with self._lock:
            rule = self._rules.get(site)
            if rule is None or not (rule.drop or rule.dup or rule.reorder):
                return items
            rng = self._rng(site, stream)
            key = (site, stream)
            held = self._held.get(key, [])
            out: List[Any] = []
            still: List[Any] = []
            for it in held:
                (out if rng.random() < 0.5 else still).append(it)
            for it in items:
                if rule.drop and rng.random() < rule.drop:
                    self._stat(site, "dropped")
                    continue
                if rule.reorder and rng.random() < rule.reorder:
                    still.append(it)
                    self._stat(site, "reordered")
                    continue
                out.append(it)
                if rule.dup and rng.random() < rule.dup:
                    out.append(it)
                    self._stat(site, "duplicated")
            if rule.reorder and len(out) > 1 and rng.random() < rule.reorder:
                i = rng.randrange(len(out) - 1)
                out[i], out[i + 1] = out[i + 1], out[i]
            if still:
                self._held[key] = still
            else:
                self._held.pop(key, None)
            return out

    def drain(self, site: str, stream: str = "") -> List[Any]:
        """Release every held-back (reordered) message for a stream."""
        with self._lock:
            return self._held.pop((site, stream), [])

    def pending(self, site: str) -> int:
        """Total held-back messages across a site's streams."""
        with self._lock:
            return sum(len(v) for (s, _), v in self._held.items() if s == site)


# -- the process-wide plan ---------------------------------------------------

_installed: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_spec: Optional[str] = None


def active() -> Optional[FaultPlan]:
    """The active plan: an installed one, else one parsed from
    ``PERITEXT_FAULTS`` (re-parsed with fresh counters if the spec changes)."""
    global _env_plan, _env_spec
    if _installed is not None:
        return _installed
    spec = os.environ.get("PERITEXT_FAULTS")
    if not spec:
        return None
    if spec != _env_spec:
        # Parse BEFORE caching the spec: a malformed spec must raise on
        # every use, not once-then-silently-inject-nothing.
        _env_plan = FaultPlan.from_spec(spec)
        _env_spec = spec
    return _env_plan


def install(plan: "FaultPlan | str") -> FaultPlan:
    """Install a plan process-wide (overrides any ``PERITEXT_FAULTS`` env)."""
    global _installed
    if isinstance(plan, str):
        plan = FaultPlan.from_spec(plan)
    _installed = plan
    return plan


def reset() -> None:
    """Remove any installed plan and forget the env-parsed one (so a spec
    still in the env re-parses with fresh counters on next use)."""
    global _installed, _env_plan, _env_spec
    _installed = None
    _env_plan = None
    _env_spec = None


@contextlib.contextmanager
def injected(plan: "FaultPlan | str"):
    """Scoped installation: ``with faults.injected("device_launch:fail=1"):``."""
    global _installed
    prev = _installed
    current = install(plan)
    try:
        yield current
    finally:
        _installed = prev


def fire(site: str) -> None:
    plan = active()
    if plan is not None:
        plan.fire(site)


def filter_stream(site: str, items: Iterable[Any], stream: str = "") -> List[Any]:
    plan = active()
    if plan is None:
        return list(items)
    return plan.filter_stream(site, items, stream)


def take(site: str, action: str) -> bool:
    plan = active()
    return plan is not None and plan.take(site, action)
