"""Mesh-sharded serving: partition sessions across N universe shards.

PR 10's :class:`~peritext_tpu.runtime.serve.ServePlane` batches every
session into ONE universe behind one scheduler — one ingest lane is the
fleet's throughput ceiling, and every cohort launch sweeps the whole
``[R, C]`` device plane even when the batch target only advances a
fraction of the rows.  Collabs (PAPERS.md) makes the case that CRDT
serving scales by composing many small independent replication domains;
Eg-walker argues for keeping per-shard hot-path state small.  This module
is that tier: a :class:`ShardedServePlane` that

- **partitions sessions across N universe shards** (one
  :class:`TpuUniverse` + one deadline-aware :class:`ServePlane` scheduler
  per shard, so cohort launches on different shards proceed
  independently — per-launch device work scales with the SHARD width,
  not the fleet width);
- **places one shard per mesh slice**: shard universes are created under
  ``jax.default_device`` on the slices :func:`peritext_tpu.parallel.mesh.
  mesh_slices` carves out of the device mesh (round-robin when shards
  outnumber devices), and a multi-device slice can optionally GSPMD-shard
  its universe's replica axis over the slice (``mesh_within_shard``);
- **pads shard widths into pow2 shape buckets** (``bucket="pow2"``, the
  default): a shard fronting n sessions runs a pow2(n)-wide universe
  (inert ``__pad…`` replicas carry no traffic), so unevenly-loaded
  shards still share ONE compiled program set process-wide and the
  fleet-wide jit cache stays bounded — ``serve.shard.<i>.
  compile_cache_{hit,miss}`` (plus the plane-global aggregate) is the
  measure;
- **wires cross-shard anti-entropy** through the existing pubsub/sync
  machinery: sessions declaring the same ``doc`` form a replication
  group with a shared gap-tolerant group log and a
  :class:`~peritext_tpu.runtime.pubsub.Publisher` — every client submit
  fans out live to the sibling sessions on other shards (through the
  ``pubsub_deliver`` chaos site, so drops/dups/reorders exercise each
  shard's causal admission gate), and :meth:`ShardedServePlane.
  anti_entropy` redelivers each member's missing contiguous suffix so
  replicas of the same document on different shards converge
  byte-identically (tests/test_serve_shard.py pins it under chaos,
  breaker fast-fail, and the degrade path; ``fuzz --serve --shards K``
  soaks it).

Byte-identity stays the hard wall: each session's concatenated patch
stream equals direct per-change ingest of exactly what that session was
handed (client submits + cross-shard deliveries), because every shard is
a full ServePlane with the same admission gate.

Manual mode (``start=False``) steps/drains every shard deterministically;
threaded mode runs one scheduler thread per shard.  Env defaults:
``PERITEXT_SERVE_SHARDS`` (shard count), ``PERITEXT_SERVE_SHARD_BUCKET``
(``pow2`` | ``exact``), ``PERITEXT_SERVE_PLACEMENT`` (``rr`` | ``load`` —
new sessions round-robin or join the least-loaded shard),
``PERITEXT_ELASTIC=1`` (attach the SLO-driven autoscaler,
runtime/elastic.py), plus the per-shard planes' own ``PERITEXT_SERVE_*``
knobs.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.runtime import telemetry
from peritext_tpu.runtime.pubsub import Publisher
from peritext_tpu.runtime.serve import (
    ServePlane,
    ServeSession,
    Submission,
    _bucket_pow2,
    _env_int,
)

Change = Dict[str, Any]

_log = logging.getLogger(__name__)

BUCKET_POW2 = "pow2"
BUCKET_EXACT = "exact"
_BUCKETS = (BUCKET_POW2, BUCKET_EXACT)

PLACEMENT_RR = "rr"
PLACEMENT_LOAD = "load"
_PLACEMENTS = (PLACEMENT_RR, PLACEMENT_LOAD)


class ParkedSubmission:
    """Future handed to a client whose submit landed while its session was
    mid-migration (runtime/elastic.py).  The migration's commit (or
    rollback) replays the park buffer onto the surviving inner session and
    binds each wrapper to the real :class:`~peritext_tpu.runtime.serve.
    Submission`; ``result``/``done`` then delegate, so callers cannot tell
    a parked submit from a direct one."""

    __slots__ = ("_bound", "_sub", "_error")

    def __init__(self) -> None:
        self._bound = threading.Event()
        self._sub: Any = None
        self._error: Optional[BaseException] = None

    def _bind(self, sub: Any) -> None:
        self._sub = sub
        self._bound.set()

    def _reject(self, error: BaseException) -> None:
        self._error = error
        self._bound.set()

    def done(self) -> bool:
        if not self._bound.is_set():
            return False
        return True if self._error is not None else self._sub.done()

    def result(self, timeout: Optional[float] = None):
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        if not self._bound.wait(timeout):
            raise TimeoutError(
                f"parked submission still migrating after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        remaining = (
            None if deadline is None else max(0.0, deadline - _time.monotonic())
        )
        return self._sub.result(timeout=remaining)


class _GroupLog:
    """Gap-tolerant per-document change log for cross-shard anti-entropy.

    Unlike :class:`~peritext_tpu.runtime.log.ChangeLog` (strictly
    sequential appends), submissions may arrive with causal gaps (chaotic
    delivery routed a suffix to one shard before its prefix): every
    change is held by ``(actor, seq)``, and redelivery hands out each
    actor's **contiguous** prefix beyond the receiver's clock — exactly
    what a shard's admission gate can use.  A same-key record that
    differs byte-for-byte is a forked actor history and must surface.
    """

    def __init__(self) -> None:
        self._by_key: Dict[Tuple[str, int], Change] = {}

    def record(self, change: Change) -> None:
        key = (change["actor"], change["seq"])
        prev = self._by_key.get(key)
        if prev is None:
            self._by_key[key] = change
        elif prev != change:
            raise ValueError(
                f"conflicting change recorded for {key}: forked actor history"
            )

    def contiguous(self, target_clock: Dict[str, int]) -> List[Change]:
        """Each actor's contiguous run of changes past ``target_clock``,
        in per-actor seq order (the shape ``ChangeLog.missing_changes``
        hands to the gate)."""
        out: List[Change] = []
        actors = sorted({a for a, _ in self._by_key})
        for actor in actors:
            seq = target_clock.get(actor, 0) + 1
            while (actor, seq) in self._by_key:
                out.append(self._by_key[(actor, seq)])
                seq += 1
        return out


class ShardSession:
    """One client session on the sharded plane: wraps the shard-local
    :class:`ServeSession` and, for ``doc``-grouped sessions, fans every
    client submit out to the sibling sessions on other shards."""

    def __init__(
        self,
        plane: "ShardedServePlane",
        inner: ServeSession,
        shard: int,
        doc: Optional[str],
    ) -> None:
        self._plane = plane
        self._inner = inner
        self.shard = shard
        self.doc = doc
        self.name = inner.name
        self.replica = inner.replica
        # Live-migration parking (runtime/elastic.py): non-None while a
        # migration of THIS session is mid-protocol — deliveries buffer
        # here and the commit/rollback replays them onto the surviving
        # inner session.  None on the hot path, so submit()/_deliver()
        # pay exactly one attribute check when elasticity is off.
        self._parked: Optional[List[Tuple[List[Change], Optional[ParkedSubmission]]]] = None
        # Document-lifecycle state (runtime/lifecycle.py): True while this
        # session's replica row is evicted to a durable checkpoint.  A
        # client submit to a cold session transparently hydrates it; cross-
        # shard deliveries to a cold session drop (the group log holds
        # them, and hydration replays the tail through the admission gate).
        self._cold = False

    @property
    def patch_log(self):
        return self._inner.patch_log

    def pending(self) -> int:
        return self._inner.pending()

    def submit(
        self,
        changes: Sequence[Change],
        wait: bool = False,
        timeout: Optional[float] = None,
    ):
        """Admit a client batch on this session's shard, then (for a
        ``doc`` group) record it in the group log and publish it to the
        sibling sessions on other shards — one change per publish, so
        per-link chaos (drop/dup/reorder) lands on each sibling's
        admission gate independently."""
        changes = list(changes)
        lc = self._plane.lifecycle
        cold = False
        if lc is not None and self._cold:
            # Hydrate-on-submit BEFORE recording: the hydration tail
            # replays whatever the logs already hold, so this batch must
            # not be logged yet — its patches belong to the Submission
            # future minted below, not to the anonymous tail replay.
            # ``pending=`` additionally excludes the batch from the tail
            # should it already be logged (a parked replay re-entering).
            lc.ensure_resident(self, pending=changes)
            cold = True
        if lc is not None and changes:
            # Lifecycle log + LRU touch BEFORE admission (mirrors the
            # group-log record-then-admit contract): a change must be
            # logged before any admission-side chaos can drop it, so a
            # later hydration can still replay it.
            lc._observe(self, changes)
        if self.doc is not None and changes:
            # Record into the group log BEFORE admission: a forked actor
            # history must reject loudly up front, never after the local
            # shard already accepted the submission.
            self._plane._record(self, changes)
        if self._parked is not None:
            sub = self._plane._park(self, changes)
        else:
            sub = self._inner.submit(changes)
            if lc is not None and isinstance(sub, Submission):
                sub.lat_class = "cold" if cold else "warm"
        if self.doc is not None and changes:
            self._plane._fan_out(self, changes)
        if wait:
            return sub.result(timeout=timeout)
        return sub

    def _deliver(self, changes: Sequence[Change]) -> None:
        """Cross-shard delivery entry (live fan-out, anti-entropy): drops
        while this session is evicted (the group log already holds the
        change — hydration replays the contiguous tail), parks during a
        migration of this session, else straight to the shard-local
        admission lane."""
        if self._cold:
            return
        if self._parked is not None:
            self._plane._park(self, list(changes), deliver=True)
            return
        self._inner.submit(changes)


class _Shard:
    """One shard slot: a lazily-created universe (first session brings it
    up on the shard's mesh slice) plus its ServePlane scheduler."""

    __slots__ = ("index", "devices", "universe", "plane", "real", "pad_ids", "pads_minted")

    def __init__(self, index: int) -> None:
        self.index = index
        # Mesh slice, resolved lazily at first universe creation (a
        # universe_factory plane never touches jax at all).
        self.devices: Optional[List[Any]] = None
        self.universe: Any = None
        self.plane: Optional[ServePlane] = None
        self.real: List[str] = []  # replicas fronted by sessions
        self.pad_ids: List[str] = []  # live inert pow2-bucket padding rows
        self.pads_minted = 0  # monotonic counter so dropped ids never reuse


class ShardedServePlane:
    """N universe shards behind one session-routing facade (see the
    module docstring).  ``shards`` defaults to ``PERITEXT_SERVE_SHARDS``;
    the per-shard scheduler knobs (batch target / deadline / quantum /
    on_open) pass straight through to each shard's :class:`ServePlane`."""

    def __init__(
        self,
        shards: Optional[int] = None,
        *,
        batch_target: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        quantum: Optional[int] = None,
        on_open: Optional[str] = None,
        start: bool = True,
        name: str = "serve",
        bucket: Optional[str] = None,
        placement: Optional[str] = None,
        capacity: int = 256,
        max_mark_ops: int = 64,
        universe_factory: Optional[Callable[[List[str], int], Any]] = None,
        devices: Optional[Sequence[Any]] = None,
        mesh_within_shard: bool = False,
    ) -> None:
        n = shards if shards is not None else _env_int("PERITEXT_SERVE_SHARDS", 1)
        if n < 1:
            raise ValueError(f"shards must be >= 1, got {n}")
        bucket = bucket or os.environ.get("PERITEXT_SERVE_SHARD_BUCKET", BUCKET_POW2)
        if bucket not in _BUCKETS:
            raise ValueError(
                f"unknown bucket policy {bucket!r}; known: {', '.join(_BUCKETS)}"
            )
        placement = placement or os.environ.get(
            "PERITEXT_SERVE_PLACEMENT", PLACEMENT_RR
        )
        if placement not in _PLACEMENTS:
            raise ValueError(
                f"unknown placement policy {placement!r}; "
                f"known: {', '.join(_PLACEMENTS)}"
            )
        self.name = name
        self.bucket = bucket
        self.placement = placement
        self._capacity = capacity
        self._max_mark_ops = max_mark_ops
        self._universe_factory = universe_factory
        self._mesh_within_shard = mesh_within_shard
        self._plane_kw = dict(
            batch_target=batch_target,
            deadline_ms=deadline_ms,
            quantum=quantum,
            on_open=on_open,
        )
        self._start = start
        self._lock = threading.RLock()
        self._devices = devices
        self._slices: Optional[List[List[Any]]] = None
        self.shards: List[_Shard] = [_Shard(i) for i in range(n)]
        self._sessions: Dict[str, ShardSession] = {}
        self._by_replica: Dict[str, ShardSession] = {}
        self._next_shard = 0
        # doc -> replication group: gap-tolerant log + live pubsub fan-out.
        self._docs: Dict[str, Dict[str, Any]] = {}
        # Fleet view on the ops surface (ISSUE 13): per-shard occupancy +
        # the compiled-shape pressure (the UNION across shards — equal
        # widths share programs).  The per-shard ServePlanes contribute
        # their own per-session "serve" entries.
        telemetry.register_status_source("serve_shards", self._status)
        if telemetry.enabled:
            telemetry.gauge("serve.shards", n)
        # SLO-driven autoscaler (ISSUE 17): PERITEXT_ELASTIC=1 attaches
        # the control loop; elastic.py takes the plane as an argument, so
        # no import cycle.  Off by default — the serving hot paths then
        # pay only the one _parked attribute check above.
        self.elastic: Any = None
        if os.environ.get("PERITEXT_ELASTIC", "") not in ("", "0"):
            from peritext_tpu.runtime.elastic import ElasticController

            self.elastic = ElasticController(self, start=start)
        # Multi-tenant document lifecycle (ISSUE 20): PERITEXT_LIFECYCLE=1
        # attaches the LRU evict/hydrate reaper; lifecycle.py takes the
        # plane as an argument, same no-cycle pattern as elastic.  Off by
        # default — submit/_deliver then pay one attribute check each.
        self.lifecycle: Any = None
        if os.environ.get("PERITEXT_LIFECYCLE", "") not in ("", "0"):
            from peritext_tpu.runtime.lifecycle import DocLifecycle

            self.lifecycle = DocLifecycle(self, start=start)

    def _status(self) -> Dict[str, Any]:
        with self._lock:
            shards: List[Dict[str, Any]] = []
            shapes: set = set()
            for shard in self.shards:
                if shard.plane is None:
                    shards.append({"shard": shard.index, "sessions": 0})
                    continue
                shapes |= shard.plane.shape_keys()
                # Per-shard pending is read under the INNER plane's lock
                # (facade-lock -> plane-lock, the established order): a
                # concurrent session() on that plane mutates _sessions
                # under the plane lock, and an unlocked dict iteration
                # here would intermittently blow up the whole status tick.
                with shard.plane._lock:
                    pending = sum(
                        s._pending for s in shard.plane._sessions.values()
                    )
                shards.append(
                    {
                        "shard": shard.index,
                        "sessions": len(shard.real),
                        "width": len(shard.universe.replica_ids),
                        "pads": len(shard.pad_ids),
                        "flushes": shard.plane.stats["flushes"],
                        "pending": pending,
                    }
                )
            return {
                "plane": self.name,
                "shards": shards,
                "doc_groups": len(self._docs),
                "fleet_compiled_shapes": len(shapes),
            }

    # -- shard provisioning --------------------------------------------------

    def _mint_pads(self, shard: _Shard, count: int) -> List[str]:
        ids = [
            f"__pad_{shard.index}_{shard.pads_minted + k}" for k in range(count)
        ]
        shard.pads_minted += count
        shard.pad_ids.extend(ids)
        return ids

    def _make_universe(self, shard: _Shard, replica_ids: List[str]) -> Any:
        if self._universe_factory is not None:
            return self._universe_factory(replica_ids, shard.index)
        import jax

        from peritext_tpu.ops import TpuUniverse

        if self._slices is None:
            # First backend touch happens here, not at plane construction
            # (a factory-backed plane must stay jax-free; on a wedged
            # relay, device enumeration is the hang — CLAUDE.md quirk).
            from peritext_tpu.parallel.mesh import mesh_slices

            self._slices = mesh_slices(len(self.shards), devices=self._devices)
        shard.devices = list(self._slices[shard.index])
        # One shard per mesh slice: the universe's device planes live on
        # the slice's lead device (a multi-device slice additionally
        # GSPMD-shards the replica axis below).
        with jax.default_device(shard.devices[0]):
            return TpuUniverse(
                replica_ids,
                capacity=self._capacity,
                max_mark_ops=self._max_mark_ops,
            )

    def _reshard_slice(self, shard: _Shard) -> None:
        """GSPMD-shard the shard universe's replica axis over its mesh
        slice (opt-in; only when the width divides the slice — pow2
        buckets make that the steady state)."""
        if (
            not self._mesh_within_shard
            or shard.devices is None  # factory-backed: placement is the factory's
            or len(shard.devices) < 2
        ):
            return
        width = len(shard.universe.replica_ids)
        if width % len(shard.devices) != 0:
            return  # re-judged after the next width change
        from peritext_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(shard.devices, len(shard.devices), 1)
        shard.universe.shard(mesh, shard_seq=False)

    def _provision_locked(self, shard: _Shard, replica: str) -> None:
        """Bring ``replica`` up on ``shard``, holding the universe width
        EXACTLY to the bucket policy: pow2 width = pow2(real sessions),
        the inert pad rows making up the difference.  A real replica
        arriving while pads exist consumes one (drop pad + add real, so
        the width — and therefore the compiled program shape — does not
        move); past the bucket boundary the width doubles and fresh pads
        fill it.  On a running shard the mutation runs under the plane's
        flush quiescence barrier — ``add/drop_replicas`` rebuild the
        device state a concurrent launch would be reading."""
        shard.real.append(replica)
        target = (
            _bucket_pow2(len(shard.real))
            if self.bucket == BUCKET_POW2
            else len(shard.real)
        )
        if shard.universe is None:
            ids = [replica] + self._mint_pads(shard, target - 1)
            shard.universe = self._make_universe(shard, ids)
            shard.plane = ServePlane(
                shard.universe,
                start=self._start,
                name=f"{self.name}.shard{shard.index}",
                shard=shard.index,
                **self._plane_kw,
            )
            self._reshard_slice(shard)
            return

        def mutate() -> None:
            if shard.pad_ids:
                # Common case inside a bucket: hand a pad row to the
                # joining session — pure bookkeeping, width (and the
                # compiled program shape) pinned, no state rebuild.
                shard.universe.rename_replica(shard.pad_ids.pop(), replica)
                return
            width = len(shard.universe.replica_ids)
            grow = [replica]
            if target > width + 1:
                grow += self._mint_pads(shard, target - width - 1)
            shard.universe.add_replicas(grow)
            self._reshard_slice(shard)

        shard.plane.run_quiesced(mutate)

    def _bucket_target(self, shard: _Shard) -> int:
        return max(
            1,
            _bucket_pow2(len(shard.real))
            if self.bucket == BUCKET_POW2
            else len(shard.real),
        )

    def _evacuate_locked(self, shard: _Shard, replica: str) -> None:
        """Remove a migrated-away real replica from its shard, holding the
        width to the bucket policy — the inverse of
        :meth:`_provision_locked`.  ``drop_replicas`` refuses to empty a
        universe, so a lone row swaps for a fresh pad instead; excess pads
        past the (possibly shrunken) bucket drop with the row in ONE
        gather where they can."""
        shard.real.remove(replica)
        target = self._bucket_target(shard)

        def mutate() -> None:
            uni = shard.universe
            width = len(uni.replica_ids)
            if target > width - 1:
                # Dropping the row would under-shoot the bucket (or empty
                # the universe): pad up first so the drop lands on-width.
                uni.add_replicas(self._mint_pads(shard, target - (width - 1)))
            drop = [replica]
            excess = len(uni.replica_ids) - 1 - target
            while excess > 0 and shard.pad_ids:
                drop.append(shard.pad_ids.pop())
                excess -= 1
            uni.drop_replicas(drop)
            self._reshard_slice(shard)

        shard.plane.run_quiesced(mutate)

    def _unprovision_locked(self, shard: _Shard, replica: str) -> None:
        """Roll a provisioned-but-unbound replica row back out (migration
        rollback): an untouched row rebinds to a fresh pad (width pinned,
        zero device work); a row the failed import already wrote drops the
        hard way."""
        shard.real.remove(replica)

        def mutate() -> None:
            uni = shard.universe
            i = uni.index_of[replica]
            if not uni.clocks[i]:
                uni.rename_replica(replica, self._mint_pads(shard, 1)[0])
            else:
                uni.add_replicas(self._mint_pads(shard, 1))
                uni.drop_replicas([replica])
            # Trim pads past the bucket so rollback restores the exact
            # pre-provision width (compiled-shape pressure unchanged).
            target = self._bucket_target(shard)
            drop: List[str] = []
            while len(uni.replica_ids) - len(drop) > target and shard.pad_ids:
                drop.append(shard.pad_ids.pop())
            if drop:
                uni.drop_replicas(drop)
            self._reshard_slice(shard)

        shard.plane.run_quiesced(mutate)

    # -- sessions ------------------------------------------------------------

    def session(
        self,
        name: str,
        replica: str,
        *,
        doc: Optional[str] = None,
        shard: Optional[int] = None,
        **session_kw: Any,
    ) -> ShardSession:
        """Open a session fronting ``replica`` on a shard (explicit
        ``shard=`` pins it; the default round-robins across shards so
        load — and a doc group's members — spread over the fleet).
        ``doc`` names the replication group for cross-shard anti-entropy;
        the remaining kwargs are :meth:`ServePlane.session`'s."""
        if self.lifecycle is not None:
            # Capacity-pressure eviction BEFORE the facade lock (the evict
            # protocol takes it): admitting this session must not push the
            # resident population past the lifecycle watermark.
            self.lifecycle._admission_pressure(exclude=name)
        with self._lock:
            if name in self._sessions:
                raise ValueError(f"session {name!r} already exists")
            if replica in self._by_replica:
                raise ValueError(
                    f"replica {replica!r} is already fronted by session "
                    f"{self._by_replica[replica].name!r}"
                )
            if shard is None:
                if self.placement == PLACEMENT_LOAD:
                    shard = self._least_loaded_locked()
                else:
                    shard = self._next_shard
                    self._next_shard = (self._next_shard + 1) % len(self.shards)
            if not (0 <= shard < len(self.shards)):
                raise ValueError(
                    f"shard {shard} out of range [0, {len(self.shards)})"
                )
            slot = self.shards[shard]
            self._provision_locked(slot, replica)
            inner = slot.plane.session(name, replica, **session_kw)
            sess = ShardSession(self, inner, shard, doc)
            self._sessions[name] = sess
            self._by_replica[replica] = sess
            if doc is not None:
                group = self._docs.get(doc)
                if group is None:
                    group = self._docs[doc] = {
                        "log": _GroupLog(),
                        "publisher": Publisher(),
                        "members": [],
                    }
                group["members"].append(sess)
                # Deliveries route through _deliver so a mid-migration
                # sibling parks them instead of racing the row handoff.
                group["publisher"].subscribe(
                    name, lambda change, s=sess: s._deliver([change])
                )
            if self.lifecycle is not None:
                self.lifecycle._admitted(sess)
            if telemetry.enabled:
                telemetry.gauge("serve.sessions", len(self._sessions))
                telemetry.counter(f"serve.shard.{shard}.sessions")
        return sess

    # -- placement + load ----------------------------------------------------

    def _shard_load_locked(self, shard: _Shard) -> int:
        """One shard's admission load: pending (admitted, unapplied)
        changes across its lanes plus its session count — the tie-break
        metric placement and the autoscaler agree on."""
        if shard.plane is None:
            return 0
        with shard.plane._lock:
            pending = sum(s._pending for s in shard.plane._sessions.values())
        return pending + len(shard.real)

    def _least_loaded_locked(self) -> int:
        """The ``load`` placement policy: the shard minimizing (load,
        sessions, index) — deterministic, and biased toward genuinely
        empty shards over merely-idle ones."""
        return min(
            range(len(self.shards)),
            key=lambda i: (
                self._shard_load_locked(self.shards[i]),
                len(self.shards[i].real),
                i,
            ),
        )

    # -- migration parking (runtime/elastic.py) ------------------------------

    def _park(
        self,
        sess: ShardSession,
        changes: List[Change],
        deliver: bool = False,
    ):
        """Buffer a delivery that raced a live migration.  Re-checks the
        parked flag under the facade lock: a caller that read a stale flag
        after the migration already unparked routes straight to the (by
        then rebound) inner session instead of stranding the changes in a
        dead buffer."""
        with self._lock:
            if sess._parked is not None:
                wrapper = None if deliver else ParkedSubmission()
                sess._parked.append((changes, wrapper))
                if telemetry.enabled:
                    telemetry.counter("elastic.parked_deliveries")
                return wrapper
            cold = sess._cold
        if cold:
            # The protocol that parked us committed an EVICTION while this
            # call raced it: a delivery simply drops (the group log holds
            # it for the hydration tail); a client submit hydrates and
            # admits like any other cold submit.
            if deliver:
                return None
            if self.lifecycle is not None:
                # pending=: this batch is already in the logs (recorded
                # before parking), but its patches belong to the future
                # minted just below — keep it out of the hydration tail.
                self.lifecycle.ensure_resident(sess, pending=changes)
        return sess._inner.submit(changes)

    # -- cross-shard anti-entropy --------------------------------------------

    def _record(self, sess: ShardSession, changes: List[Change]) -> None:
        group = self._docs[sess.doc]
        with self._lock:
            for change in changes:
                group["log"].record(change)

    def _fan_out(self, sess: ShardSession, changes: List[Change]) -> None:
        """Live cross-shard delivery, best-effort by design: the change
        is already durably in the group log and admitted on its home
        shard, so a failing link (chaos fail/wedge, a sibling's
        backpressure rejection) must never surface to the submitter or
        void its future — anti-entropy redelivers what the live fan-out
        missed.  A failed publish skips that change's remaining siblings
        (Publisher fans per change); later changes still go out."""
        group = self._docs[sess.doc]
        if telemetry.enabled:
            telemetry.counter("serve.fanout_changes", len(changes))
        for change in changes:
            try:
                group["publisher"].publish(sess.name, change)
            except Exception:
                if telemetry.enabled:
                    telemetry.counter("serve.fanout_failures")
                _log.warning(
                    "doc group %r: live fan-out of (%s, %s) from %s failed; "
                    "anti-entropy will redeliver",
                    sess.doc, change.get("actor"), change.get("seq"),
                    sess.name, exc_info=True,
                )

    def anti_entropy(self) -> int:
        """Redeliver every doc-group member's missing contiguous suffix
        from the group log (fault-free, dedup-idempotent — the shard
        gates drop what already landed).  Returns the number of changes
        redelivered; callers drain afterwards.

        Locking: membership snapshots under the facade lock; each
        member's universe clock is then read through its own plane's
        flush-quiescence barrier with NO facade lock held (one shard's
        slow or wedged launch must not stall submits fleet-wide), and the
        group-log read retakes the facade lock briefly.  No lock is ever
        nested inside another here, so no ordering constraint arises; a
        clock read racing a later submit only redelivers changes the
        gate will drop."""
        with self._lock:
            groups = [(g, list(g["members"])) for g in self._docs.values()]
        pending: List[Tuple[ShardSession, List[Change]]] = []
        for group, members in groups:
            for sess in members:
                if sess._parked is not None or sess._cold:
                    # Mid-migration: the commit replays the group-log tail
                    # itself; redelivering here would race the row handoff.
                    # Cold (evicted): the row is gone — hydration replays
                    # the tail, and redelivering would just rehydrate it.
                    continue
                shard = self.shards[sess.shard]
                if shard.plane is None:
                    continue
                try:
                    clock = shard.plane.run_quiesced(
                        lambda s=shard, r=sess.replica: s.universe.clock(r)
                    )
                except KeyError:
                    # The row moved shards between the membership snapshot
                    # and this read; the next pass sees the new home.
                    continue
                with self._lock:
                    missing = group["log"].contiguous(clock)
                if missing:
                    pending.append((sess, missing))
        redelivered = 0
        for sess, missing in pending:
            sess._deliver(missing)
            redelivered += len(missing)
        if redelivered and telemetry.enabled:
            telemetry.counter("serve.anti_entropy_changes", redelivered)
        return redelivered

    # -- driving -------------------------------------------------------------

    def _planes(self) -> List[ServePlane]:
        return [s.plane for s in self.shards if s.plane is not None]

    def step(self) -> bool:
        """Manual mode: one cohort-formation step on every shard.
        Returns True when any shard flushed."""
        worked = False
        for plane in self._planes():
            worked = plane.step() or worked
        return worked

    def drain(self, max_steps: int = 1000) -> int:
        """Manual mode: flush every shard until all lanes empty or no
        shard can progress.  Returns still-pending submissions fleet-wide
        (0 = fully drained).  Shard drains are independent: cross-shard
        fan-out happens at submit time, never during a flush, so one
        shard's flush can never unblock another's deferred lane."""
        return sum(plane.drain(max_steps) for plane in self._planes())

    def flush_and_wait(self, timeout: float = 30.0) -> None:
        for plane in self._planes():
            plane.flush_and_wait(timeout=timeout)

    def close(self, reject_pending: bool = True) -> None:
        if self.lifecycle is not None:
            self.lifecycle.close()
        if self.elastic is not None:
            self.elastic.close()
        for plane in self._planes():
            plane.close(reject_pending=reject_pending)

    def __enter__(self) -> "ShardedServePlane":
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.close()
        return False

    # -- introspection -------------------------------------------------------

    def shard_of(self, replica: str) -> int:
        return self._by_replica[replica].shard

    def universe_of(self, replica: str) -> Any:
        return self.shards[self.shard_of(replica)].universe

    def clock(self, replica: str) -> Dict[str, int]:
        return self.universe_of(replica).clock(replica)

    def spans(self, replica: str) -> List[Dict[str, Any]]:
        """One replica's formatted spans, routed through its shard."""
        uni = self.universe_of(replica)
        return uni.spans(replica)

    @property
    def stats(self) -> Dict[str, Any]:
        """Fleet aggregate of the per-shard plane stats, plus the
        per-shard list under ``"shards"`` and the fleet-wide distinct
        compiled-shape count (shards of equal width share programs, so
        the union — not the sum — is the jit-cache pressure)."""
        agg: Dict[str, Any] = {}
        per_shard: List[Dict[str, int]] = []
        shapes: set = set()
        for shard in self.shards:
            if shard.plane is None:
                per_shard.append({})
                continue
            per_shard.append(dict(shard.plane.stats))
            shapes |= shard.plane.shape_keys()
            for key, val in shard.plane.stats.items():
                agg[key] = agg.get(key, 0) + val
        agg["shards"] = per_shard
        agg["fleet_compiled_shapes"] = len(shapes)
        return agg
