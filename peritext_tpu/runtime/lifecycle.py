"""Multi-tenant document lifecycle: crash-safe evict/hydrate over the
sharded serving plane.

Every universe today holds its replica rows device-resident forever, so
device capacity is the tenancy wall — a fleet fronting N documents needs N
rows even when most documents are idle.  This module makes residency a
cache, not a commitment:

- :meth:`DocLifecycle.evict` checkpoints ONE session's replica row to a
  durable per-document generation directory (npz + digest sidecar, atomic
  tmp+rename writes, rotating ``keep`` generations) together with its
  causal position (the eviction-time clock), then frees the device row
  through the pow2 pad plane (``_evacuate_locked``: pad consume /
  legal shrink) under the shard's flush-quiescence barrier.  The session
  object survives — cold, with no row.

- A ``session.submit`` to a cold document transparently **hydrates** it:
  re-provision a row (pad consume / pow2 growth), import the newest
  loadable generation (digest-verified; a corrupt generation falls back
  to the previous one, and with no loadable generation at all the row
  rebuilds by full log replay from genesis), replay the log tail through
  the normal causal admission gate, rebind the session to a fresh inner
  lane (the patch log is the SAME list object, so the per-session stream
  concatenates seamlessly), then admit the triggering submission.  The
  serving API is unchanged; the only visible difference is latency,
  split into first-class ``e2e.admit_to_applied_{warm,cold}`` histograms
  (``PERITEXT_SLO``-able).

- Every pre-commit protocol step is a ``faults.fire("doc_evict")`` /
  ``faults.fire("doc_hydrate")`` chokepoint with rollback semantics
  mirroring :func:`~peritext_tpu.runtime.elastic.migrate_session`: a
  failed evict leaves the session resident and authoritative (parked
  deliveries replay verbatim onto the still-live lane); a failed hydrate
  unwinds the provisioned row and leaves the session cold (the next
  submit retries).  A SIGKILL between checkpoint write and row free just
  leaves a stale newer generation behind — the session is still
  resident, and the next successful evict writes a newer generation, so
  hydration always prefers the newest *loadable* truth.

Byte-identity is the hard wall throughout: each session's concatenated
patch stream equals direct ingest of exactly what it was handed, through
evictions, hydrations, corrupt-generation fallbacks, full replays, and
every rollback path (tests/test_lifecycle.py).  Replay never duplicates
the stream: changes at or below the eviction-time clock re-apply with
the patch sink detached (they were already streamed before eviction),
and only genuinely-new tail changes emit.

Policy: :meth:`DocLifecycle.tick` (``ElasticController``-style loop;
``PERITEXT_LIFECYCLE=1`` attaches one to every new ShardedServePlane)
evicts the least-recently-active session once it idles past
``PERITEXT_LIFECYCLE_IDLE`` seconds, and holds the fleet-wide resident
population at ``PERITEXT_LIFECYCLE_WATERMARK`` (0 = unbounded) — both at
tick time and synchronously at admission/hydration (capacity-pressure
eviction), which is what lets ``docs served / device rows`` (the tenancy
ratio, a measured line in ``obs.status()`` and the lifecycle A/B) exceed
1.0.

Sessions without a ``doc`` replication group get a lifecycle-private
gap-tolerant log fed at submit time, so the corrupt-fallback and
full-replay chains work uniformly for grouped and ungrouped sessions.

Telemetry: ``lifecycle.*`` counters, ``lifecycle.evict`` /
``lifecycle.hydrate`` flow lanes (terminal ``evicted`` / ``hydrated`` /
``rolled_back``), rate-limited ``doc_evict_failed`` / ``doc_hydrate_failed``
black-box dumps (per-doc dedupe keys), and a ``lifecycle`` block in
``obs.status()`` rendered by ``scripts/ops_top.py``.
"""
from __future__ import annotations

import collections
import io
import json
import logging
import os
import re
import threading
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from peritext_tpu.runtime import checkpoint, faults, telemetry
from peritext_tpu.runtime.serve_shard import _GroupLog

_log = logging.getLogger(__name__)

# Sidecar keys copied verbatim from the export_replica payload.
_SIDECAR_KEYS = (
    "replica", "capacity", "max_mark_ops", "clock", "length",
    "mark_count", "store", "text_obj", "actors", "attrs", "digest",
)
_LOAD_ERRORS = (OSError, ValueError, KeyError, zipfile.BadZipFile)


class EvictionError(RuntimeError):
    """An eviction failed and was rolled back; the session is still
    resident and its shard authoritative."""


class HydrationError(RuntimeError):
    """A hydration failed and was rolled back; the session is still cold
    (the next submit retries the protocol)."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class DocLifecycle:
    """Evict/hydrate layer + LRU reaper over one ShardedServePlane (module
    docstring).  Construct directly (``start=False`` + manual ``tick()``
    for deterministic tests) or let ``PERITEXT_LIFECYCLE=1`` attach one.
    """

    def __init__(
        self,
        plane: Any,
        *,
        directory: Optional[str] = None,
        idle_s: Optional[float] = None,
        watermark: Optional[int] = None,
        interval: Optional[float] = None,
        cooldown: Optional[float] = None,
        keep: Optional[int] = None,
        start: bool = True,
    ) -> None:
        self.plane = plane
        plane.lifecycle = self
        if directory is None:
            directory = os.environ.get("PERITEXT_LIFECYCLE_DIR", "")
        if not directory:
            import tempfile

            directory = tempfile.mkdtemp(prefix=f"peritext-lifecycle-{plane.name}-")
        self.directory = directory
        self.idle_s = (
            idle_s if idle_s is not None
            else _env_float("PERITEXT_LIFECYCLE_IDLE", 30.0)
        )
        # Fleet-wide resident-session cap (0 = unbounded): enforced at tick
        # time AND synchronously at admission/hydration, so a bounded fleet
        # stays bounded even between ticks.
        self.watermark = int(
            watermark if watermark is not None
            else _env_float("PERITEXT_LIFECYCLE_WATERMARK", 0)
        )
        self.interval = (
            interval if interval is not None
            else _env_float("PERITEXT_LIFECYCLE_INTERVAL", 1.0)
        )
        self.cooldown = (
            cooldown if cooldown is not None
            else _env_float("PERITEXT_LIFECYCLE_COOLDOWN", 1.0)
        )
        self.keep = max(1, int(
            keep if keep is not None
            else _env_float("PERITEXT_LIFECYCLE_KEEP", 2)
        ))
        # One protocol at a time: evict, hydrate, and pressure sweeps all
        # serialize here (reentrant — hydration's own pressure sweep may
        # evict).  Never acquired while holding plane._lock.
        self._op_lock = threading.RLock()
        # Per-session lifecycle records (survive across evict/hydrate
        # cycles): replica/shard/doc, eviction-time clock, the carried
        # patch-log list object, swept-lane leftovers, session kwargs.
        self._records: Dict[str, Dict[str, Any]] = {}
        # Lifecycle-private change logs for sessions WITHOUT a doc group
        # (grouped sessions replay from the shared group log instead).
        self._logs: Dict[str, _GroupLog] = {}
        self._log_lock = threading.Lock()
        self._last_active: Dict[str, float] = {}
        self._cold_starts: collections.deque = collections.deque(maxlen=256)
        self.stats: Dict[str, int] = {
            "ticks": 0,
            "evictions": 0,
            "hydrations": 0,
            "evict_failures": 0,
            "hydrate_failures": 0,
            "rollbacks": 0,
            "corrupt_fallbacks": 0,
            "full_replays": 0,
            "pressure_evictions": 0,
            "pressure_failures": 0,
            "replayed_changes": 0,
        }
        self.last_eviction: Optional[Dict[str, Any]] = None
        self._last_action_t = float("-inf")
        self._closed = False
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        telemetry.register_status_source("lifecycle", self._status)
        if start:
            self.start()

    # -- hot-path hooks (called from serve_shard) -----------------------------

    def _observe(self, sess: Any, changes: List[Dict[str, Any]]) -> None:
        """Submit-time hook: LRU touch + (for ungrouped sessions) record
        into the lifecycle-private log BEFORE admission, so the hydration
        tail can always replay what admission-side chaos dropped."""
        self._last_active[sess.name] = time.monotonic()
        if sess.doc is None:
            with self._log_lock:
                log = self._logs.get(sess.name)
                if log is None:
                    log = self._logs[sess.name] = _GroupLog()
                for change in changes:
                    log.record(change)

    def _admitted(self, sess: Any) -> None:
        """New-session hook (under the facade lock): seed the LRU clock so
        a never-submitting session is evictable once it idles."""
        self._last_active[sess.name] = time.monotonic()

    def ensure_resident(
        self, sess: Any, pending: Optional[List[Dict[str, Any]]] = None
    ) -> bool:
        """Hydrate ``sess`` if cold (idempotent; serialized on the
        protocol lock).  ``pending`` is the batch the caller is about to
        submit with its own future — excluded from the hydration tail so
        its patches resolve on THAT future, not the anonymous replay.
        Returns True when a hydration actually ran."""
        if not sess._cold:
            return False
        with self._op_lock:
            if not sess._cold:
                return False
            self.hydrate(sess.name, _exclude=pending)
            return True

    # -- the eviction protocol ------------------------------------------------

    def evict(self, name: str, reason: str = "manual") -> None:
        """Evict session ``name``: durable checkpoint, then free the row.

        Raises :class:`EvictionError` after rolling back on any protocol
        failure (the session stays resident and authoritative); raises
        ``KeyError``/``ValueError`` for caller mistakes (unknown session,
        already cold, mid-migration) before anything is touched."""
        plane = self.plane
        with self._op_lock:
            with plane._lock:
                sess = plane._sessions.get(name)
                if sess is None:
                    raise KeyError(f"unknown session {name!r}")
                if sess._cold:
                    raise ValueError(f"session {name!r} is already evicted")
                if sess._parked is not None:
                    raise ValueError(f"session {name!r} is migrating")
                slot = plane.shards[sess.shard]
                inner = sess._inner
                # Park: deliveries buffer until commit/rollback replays them.
                sess._parked = []
            if telemetry.enabled:
                ctx = telemetry.flow(
                    "lifecycle.evict", session=name, shard=sess.shard,
                    reason=reason,
                )
                telemetry.counter("lifecycle.evictions_started")
            else:
                ctx = None
            try:
                with telemetry.span(
                    "lifecycle.evict", session=name, shard=sess.shard
                ):
                    telemetry.flow_point(ctx)
                    # Step 1: drain the source lane — the parked flag stops
                    # new admissions, so after this the lane holds only
                    # causally-undeliverable leftovers (swept at commit and
                    # grafted back at hydration).
                    faults.fire("doc_evict")
                    if slot.plane._thread is not None:
                        slot.plane.flush_and_wait()
                    else:
                        slot.plane.drain()
                    # Step 2: export the row under the shard's quiescence
                    # barrier (no cohort may be mid-launch over it).
                    faults.fire("doc_evict")
                    payload = slot.plane.run_quiesced(
                        lambda: checkpoint.export_replica(
                            slot.universe, sess.replica
                        )
                    )
                    # Step 3: persist a durable generation (atomic writes;
                    # the doc_evict:corrupt drill truncates the npz after).
                    faults.fire("doc_evict")
                    self._persist(name, payload)
                    # Step 4: the commit gate — the last point a failure
                    # can abort; past it the device row frees.  A process
                    # kill HERE (checkpoint written, row not yet freed) is
                    # safe: the session is still resident, and the stale
                    # generation is simply superseded by the next evict.
                    faults.fire("doc_evict")
            except BaseException as exc:
                with telemetry.span(
                    "lifecycle.evict_rollback", session=name,
                    error=type(exc).__name__,
                ):
                    self._evict_rollback(sess, name, exc)
                    telemetry.flow_point(ctx, terminal=True, outcome="rolled_back")
                raise EvictionError(
                    f"eviction of session {name!r} failed and rolled back: {exc}"
                ) from exc
            # COMMIT: pure host bookkeeping — no fault chokepoints, so the
            # protocol can never die half-evicted.
            with plane._lock:
                leftovers = slot.plane.evict_session(name)
                plane._evacuate_locked(slot, sess.replica)
                rec = self._records.setdefault(name, {})
                rec.update(
                    replica=sess.replica,
                    shard=sess.shard,
                    doc=sess.doc,
                    clock=dict(payload["clock"]),
                    patch_log=inner.patch_log,
                    leftovers=leftovers,
                    session_kw=dict(
                        weight=inner.weight,
                        priority=inner.priority,
                        bound=inner.bound,
                        policy=inner.policy,
                        block_timeout=inner.block_timeout,
                    ),
                )
                sess._cold = True
                buf, sess._parked = sess._parked, None
            # Parked client submits raced the eviction: route them back
            # through the session (which hydrates straight back — rare, and
            # correctness beats residency).  Parked deliveries drop: the
            # log already holds them for the hydration tail.
            for changes, wrapper in buf or []:
                if wrapper is None:
                    continue
                try:
                    sub = sess.submit(changes)
                except Exception as replay_exc:
                    wrapper._reject(replay_exc)
                    continue
                wrapper._bind(sub)
            self.stats["evictions"] += 1
            if reason == "pressure":
                self.stats["pressure_evictions"] += 1
            self.last_eviction = {
                "session": name,
                "shard": slot.index,
                "reason": reason,
                "t": time.time(),
            }
            if telemetry.enabled:
                telemetry.counter("lifecycle.evictions")
                if reason == "pressure":
                    telemetry.counter("lifecycle.pressure_evictions")
                telemetry.record(
                    "lifecycle.evict", outcome="evicted", session=name,
                    shard=slot.index, reason=reason,
                )
            # The terminal seam is spanned so the flow lane binds (the
            # trace_report schema contract — same as elastic's commit).
            with telemetry.span("lifecycle.evict_commit", session=name):
                telemetry.flow_point(ctx, terminal=True, outcome="evicted")

    def _evict_rollback(self, sess: Any, name: str, exc: BaseException) -> None:
        """Unwind a failed eviction: unpark, replay parked deliveries
        verbatim onto the still-authoritative inner lane, dump."""
        with self.plane._lock:
            buf, sess._parked = sess._parked, None
        for changes, wrapper in buf or []:
            try:
                sub = sess._inner.submit(changes)
            except Exception as replay_exc:
                if wrapper is not None:
                    wrapper._reject(replay_exc)
                else:
                    _log.warning(
                        "parked delivery replay for %s failed after evict "
                        "rollback; anti-entropy will redeliver",
                        name, exc_info=True,
                    )
                continue
            if wrapper is not None:
                wrapper._bind(sub)
        self.stats["evict_failures"] += 1
        self.stats["rollbacks"] += 1
        if telemetry.enabled:
            telemetry.counter("lifecycle.evict_failures")
            telemetry.counter("lifecycle.rollbacks")
            telemetry.record(
                "lifecycle.evict", outcome="rolled_back", session=name,
                error=type(exc).__name__,
            )
        telemetry.blackbox_dump(
            "doc_evict_failed",
            dedupe_key=f"doc_evict:{name}",
            session=name,
            error=f"{type(exc).__name__}: {exc}",
        )

    # -- the hydration protocol -----------------------------------------------

    def hydrate(
        self,
        name: str,
        _exclude: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        """Restore cold session ``name``: provision a row, import the
        newest loadable generation (corrupt generations fall back; none
        loadable → full log replay from genesis), replay the log tail
        through the causal gate, rebind the lane.  Idempotent when the
        session is already resident.  ``_exclude``: logged changes a
        caller will submit itself right after (their patches belong to
        that caller's future, so the tail must not claim them).  Raises
        :class:`HydrationError` after rolling back (the session stays
        cold)."""
        plane = self.plane
        with self._op_lock:
            with plane._lock:
                sess = plane._sessions.get(name)
                if sess is None:
                    raise KeyError(f"unknown session {name!r}")
                if not sess._cold:
                    return
                if sess._parked is not None:
                    raise ValueError(f"session {name!r} is migrating")
                rec = self._records.get(name)
                if rec is None:
                    raise KeyError(f"no lifecycle record for session {name!r}")
                slot = plane.shards[rec["shard"]]
                sess._parked = []
            # Hydrating past the watermark evicts someone else first (the
            # page-cache shape); a pressure failure must not block this
            # hydration — availability wins, the reaper catches up later.
            try:
                self._admission_pressure(exclude=name)
            except Exception:
                self.stats["pressure_failures"] += 1
                _log.warning(
                    "capacity-pressure sweep before hydrating %s failed; "
                    "hydrating anyway", name, exc_info=True,
                )
            if telemetry.enabled:
                ctx = telemetry.flow(
                    "lifecycle.hydrate", session=name, shard=slot.index,
                )
                telemetry.counter("lifecycle.hydrations_started")
            else:
                ctx = None
            t0 = time.perf_counter()
            provisioned = False
            new_inner = None
            try:
                with telemetry.span("lifecycle.hydrate", session=name):
                    telemetry.flow_point(ctx)
                    # Step 1: provision the row (pad consume / pow2 growth).
                    faults.fire("doc_hydrate")
                    with plane._lock:
                        plane._provision_locked(slot, rec["replica"])
                        provisioned = True
                    # Step 2: newest loadable generation (digest-verified;
                    # corrupt generations fall back one at a time).
                    faults.fire("doc_hydrate")
                    payload, fallbacks = self._load_latest(name)
                    if fallbacks:
                        self.stats["corrupt_fallbacks"] += fallbacks
                        if telemetry.enabled:
                            telemetry.counter(
                                "lifecycle.corrupt_fallbacks", fallbacks
                            )
                        telemetry.blackbox_dump(
                            "doc_hydrate_failed",
                            dedupe_key=f"doc_hydrate:{name}",
                            session=name,
                            corrupt_generations=fallbacks,
                            recovered="older_generation" if payload is not None
                            else "full_replay",
                        )
                    # Step 3: digest-verified import (or leave the fresh
                    # row empty: full replay rebuilds it from the log).
                    faults.fire("doc_hydrate")
                    if payload is not None:
                        with plane._lock:
                            slot.plane.run_quiesced(
                                lambda: checkpoint.import_replica(
                                    slot.universe, rec["replica"], payload
                                )
                            )
                    else:
                        self.stats["full_replays"] += 1
                        if telemetry.enabled:
                            telemetry.counter("lifecycle.full_replays")
                    # Step 4: rebind a fresh inner lane + causal replay.
                    faults.fire("doc_hydrate")
                    with plane._lock:
                        new_inner = slot.plane.session(
                            name, rec["replica"], **rec["session_kw"]
                        )
                    restored = dict(payload["clock"]) if payload is not None else {}
                    tail = self._replay_tail(
                        sess, new_inner, slot, rec, restored
                    )
                    # Step 5: the commit gate.
                    faults.fire("doc_hydrate")
            except BaseException as exc:
                with telemetry.span(
                    "lifecycle.hydrate_rollback", session=name,
                    error=type(exc).__name__,
                ):
                    self._hydrate_rollback(
                        sess, slot, rec, provisioned, new_inner, name, exc
                    )
                    telemetry.flow_point(ctx, terminal=True, outcome="rolled_back")
                raise HydrationError(
                    f"hydration of session {name!r} failed and rolled back "
                    f"(still cold): {exc}"
                ) from exc
            # COMMIT: pure host bookkeeping.
            with plane._lock:
                leftovers = rec.pop("leftovers", None) or []
                if leftovers:
                    # Causally-undeliverable submissions swept at eviction:
                    # graft the SAME Submission objects so callers' futures
                    # still resolve with their exact patches.
                    with slot.plane._work:
                        for sub in leftovers:
                            sub.session = new_inner
                            new_inner._lane.append(sub)
                            new_inner._pending += len(sub.changes)
                        slot.plane._work.notify_all()
                sess._inner = new_inner
                sess._cold = False
                buf, sess._parked = sess._parked, None
                # Future-bearing batches (the caller's pending submit +
                # parked client submits) re-submit below with their OWN
                # Submissions; the tail must not claim their patches.
                # Snapshotted under the facade lock — nothing can park
                # after this point (unparked + warm).
                claimed = {
                    (c["actor"], c["seq"]) for c in (_exclude or [])
                }
                for changes, wrapper in buf or []:
                    if wrapper is not None:
                        claimed.update((c["actor"], c["seq"]) for c in changes)
            tail = [c for c in tail if (c["actor"], c["seq"]) not in claimed]
            if tail:
                new_inner.submit(tail)
                self.stats["replayed_changes"] += len(tail)
                if telemetry.enabled:
                    telemetry.counter("lifecycle.replayed_changes", len(tail))
            # Parked client submits replay verbatim (their futures rebind);
            # parked DELIVERIES replay through the chaos filter — transport
            # loss across the handoff, the log + anti-entropy redeliver.
            for changes, wrapper in buf or []:
                if wrapper is None:
                    changes = faults.filter_stream(
                        "doc_hydrate", changes, stream=name
                    )
                try:
                    sub = new_inner.submit(changes)
                except Exception as replay_exc:
                    if wrapper is not None:
                        wrapper._reject(replay_exc)
                    continue
                if wrapper is not None:
                    wrapper._bind(sub)
            dt = time.perf_counter() - t0
            self._cold_starts.append(dt)
            self._last_active[name] = time.monotonic()
            self.stats["hydrations"] += 1
            if telemetry.enabled:
                telemetry.counter("lifecycle.hydrations")
                telemetry.observe("lifecycle.hydrate_seconds", dt)
                telemetry.record(
                    "lifecycle.hydrate", outcome="hydrated", session=name,
                    shard=slot.index,
                )
            # Spanned terminal seam: the flow lane must bind for
            # trace_report validation (the elastic commit precedent).
            with telemetry.span("lifecycle.hydrate_commit", session=name):
                telemetry.flow_point(ctx, terminal=True, outcome="hydrated")

    def _replay_tail(
        self,
        sess: Any,
        inner: Any,
        slot: Any,
        rec: Dict[str, Any],
        restored_clock: Dict[str, int],
    ) -> List[Dict[str, Any]]:
        """Replay the logged PREFIX (changes at or below the eviction-time
        clock: already streamed before eviction, so they re-apply with the
        patch sink still detached, rebuilding state without duplicating
        the stream) and reattach the carried patch log.  Returns the TAIL
        (changes past the eviction clock — arrived while cold) for the
        commit to submit once it knows which batches belong to callers'
        own futures."""
        if rec["doc"] is not None:
            group = self.plane._docs.get(rec["doc"])
            log = group["log"] if group is not None else None
            log_lock = self.plane._lock
        else:
            with self._log_lock:
                log = self._logs.get(sess.name)
            log_lock = self._log_lock
        missing: List[Dict[str, Any]] = []
        if log is not None:
            with log_lock:
                missing = log.contiguous(restored_clock)
        evict_clock = rec.get("clock") or {}
        prefix = [
            c for c in missing if c["seq"] <= evict_clock.get(c["actor"], 0)
        ]
        tail = [
            c for c in missing if c["seq"] > evict_clock.get(c["actor"], 0)
        ]
        if prefix:
            # The fresh inner session's patch_log is None here, so the
            # prefix's (re-)patches discard.  Resolve them NOW — patch
            # routing reads session.patch_log at resolution time.
            inner.submit(prefix)
            if slot.plane._thread is not None:
                slot.plane.flush_and_wait()
            else:
                slot.plane.drain()
            if inner._lane:
                raise RuntimeError(
                    f"hydration prefix replay for {sess.name!r} did not "
                    f"fully apply ({len(inner._lane)} submissions stuck)"
                )
        inner.patch_log = rec.get("patch_log")
        if prefix:
            self.stats["replayed_changes"] += len(prefix)
            if telemetry.enabled:
                telemetry.counter("lifecycle.replayed_changes", len(prefix))
        return tail

    def _hydrate_rollback(
        self,
        sess: Any,
        slot: Any,
        rec: Dict[str, Any],
        provisioned: bool,
        new_inner: Any,
        name: str,
        exc: BaseException,
    ) -> None:
        """Unwind a failed hydration: discard the half-built inner lane,
        unprovision the target row, leave the session cold.  Parked client
        submits reject (their callers retry and re-trigger hydration);
        parked deliveries drop — the log holds them."""
        with self.plane._lock:
            if new_inner is not None:
                try:
                    slot.plane.evict_session(name)
                except KeyError:
                    pass
            if provisioned:
                try:
                    self.plane._unprovision_locked(slot, rec["replica"])
                except Exception:
                    _log.warning(
                        "hydrate rollback of session %s could not "
                        "unprovision the row; shard %d carries a stray row",
                        name, slot.index, exc_info=True,
                    )
            buf, sess._parked = sess._parked, None
        for _, wrapper in buf or []:
            if wrapper is not None:
                wrapper._reject(exc)
        self.stats["hydrate_failures"] += 1
        self.stats["rollbacks"] += 1
        if telemetry.enabled:
            telemetry.counter("lifecycle.hydrate_failures")
            telemetry.counter("lifecycle.rollbacks")
            telemetry.record(
                "lifecycle.hydrate", outcome="rolled_back", session=name,
                error=type(exc).__name__,
            )
        telemetry.blackbox_dump(
            "doc_hydrate_failed",
            dedupe_key=f"doc_hydrate:{name}",
            session=name,
            error=f"{type(exc).__name__}: {exc}",
        )

    # -- the durable generation store -----------------------------------------

    def _doc_dir(self, name: str) -> str:
        return os.path.join(
            self.directory, re.sub(r"[^A-Za-z0-9._-]", "_", name)
        )

    def _generations(self, d: str) -> List[int]:
        try:
            names = os.listdir(d)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("gen-") and n.endswith(".json"):
                try:
                    out.append(int(n[4:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def _persist(self, name: str, payload: Dict[str, Any]) -> str:
        """Write one durable generation: npz of the row arrays + a JSON
        sidecar carrying the control planes and both digests (the row
        digest import verifies, and a sha256 of the npz bytes so
        truncation is caught at load).  Atomic tmp+rename for both files;
        prunes past ``keep``; then the ``doc_evict:corrupt`` drill may
        truncate the just-written npz (crash-corruption simulation)."""
        d = self._doc_dir(name)
        os.makedirs(d, exist_ok=True)
        gens = self._generations(d)
        gen = (gens[-1] + 1) if gens else 0
        base = os.path.join(d, f"gen-{gen:08d}")
        buf = io.BytesIO()
        np.savez_compressed(buf, **payload["arrays"])
        blob = buf.getvalue()
        tmp = base + ".npz.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, base + ".npz")
        import hashlib

        sidecar: Dict[str, Any] = {k: payload[k] for k in _SIDECAR_KEYS}
        sidecar["format"] = 1
        sidecar["npz_sha256"] = hashlib.sha256(blob).hexdigest()
        tmp = base + ".json.tmp"
        with open(tmp, "w") as f:
            # default=int: lengths/counts may arrive as numpy scalars.
            json.dump(sidecar, f, default=int)
        os.replace(tmp, base + ".json")
        for old in self._generations(d)[: -self.keep]:
            for suffix in (".json", ".npz"):
                try:
                    os.remove(os.path.join(d, f"gen-{old:08d}{suffix}"))
                except OSError:
                    pass
        if faults.take("doc_evict", "corrupt"):
            with open(base + ".npz", "r+b") as f:
                f.truncate(max(1, len(blob) // 2))
        return base

    def _load_generation(self, base: str) -> Dict[str, Any]:
        with open(base + ".json") as f:
            sidecar = json.load(f)
        with open(base + ".npz", "rb") as f:
            blob = f.read()
        import hashlib

        expected = sidecar.get("npz_sha256")
        if expected is not None and hashlib.sha256(blob).hexdigest() != expected:
            raise ValueError(
                f"generation {base!r}: npz digest mismatch (truncated or corrupt)"
            )
        data = np.load(io.BytesIO(blob))
        arrays = {f: data[f] for f in checkpoint._STATE_FIELDS}
        if checkpoint._row_digest(arrays) != sidecar["digest"]:
            raise ValueError(
                f"generation {base!r}: row digest mismatch (corrupt state)"
            )
        payload = {k: sidecar[k] for k in _SIDECAR_KEYS}
        payload["arrays"] = arrays
        return payload

    def _load_latest(
        self, name: str
    ) -> Tuple[Optional[Dict[str, Any]], int]:
        """Newest loadable generation's payload (or None — full replay),
        plus the number of corrupt generations skipped on the way."""
        d = self._doc_dir(name)
        fallbacks = 0
        for gen in reversed(self._generations(d)):
            base = os.path.join(d, f"gen-{gen:08d}")
            try:
                return self._load_generation(base), fallbacks
            except _LOAD_ERRORS as exc:
                fallbacks += 1
                if telemetry.enabled:
                    telemetry.record(
                        "lifecycle.hydrate", outcome="corrupt_fallback",
                        session=name, generation=gen,
                        error=type(exc).__name__,
                    )
                _log.warning(
                    "lifecycle generation %d for %s unreadable (%s: %s); "
                    "falling back", gen, name, type(exc).__name__, exc,
                )
                continue
        return None, fallbacks

    # -- policy: capacity pressure + the LRU reaper ---------------------------

    def _resident_locked(self) -> List[str]:
        return [
            n for n, s in self.plane._sessions.items() if not s._cold
        ]

    def _lru_victim(self, exclude: Optional[str] = None) -> Optional[str]:
        """Least-recently-active resident session eligible for eviction
        (not parked, not cold, not ``exclude``)."""
        with self.plane._lock:
            candidates = [
                n for n, s in self.plane._sessions.items()
                if not s._cold and s._parked is None and n != exclude
            ]
        if not candidates:
            return None
        return min(
            candidates,
            key=lambda n: (self._last_active.get(n, float("-inf")), n),
        )

    def _admission_pressure(self, exclude: Optional[str] = None) -> None:
        """Synchronous watermark enforcement: evict LRU residents until
        admitting one more session keeps the fleet at the watermark."""
        if self.watermark <= 0:
            return
        with self._op_lock:
            while True:
                with self.plane._lock:
                    resident = len(self._resident_locked())
                if resident < self.watermark:
                    return
                victim = self._lru_victim(exclude)
                if victim is None:
                    return
                try:
                    self.evict(victim, reason="pressure")
                except (EvictionError, ValueError, KeyError):
                    # Rolled back (or the fleet changed underneath): give
                    # up this sweep — availability beats boundedness, and
                    # the reaper tick retries.
                    self.stats["pressure_failures"] += 1
                    if telemetry.enabled:
                        telemetry.counter("lifecycle.pressure_failures")
                    return

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One reaper decision (cooldown-gated): watermark overflow evicts
        the LRU resident; otherwise the LRU resident idle past ``idle_s``
        with an empty lane evicts.  Returns "evict" or None."""
        self.stats["ticks"] += 1
        if telemetry.enabled:
            telemetry.counter("lifecycle.ticks")
        t = time.monotonic() if now is None else now
        if t - self._last_action_t < self.cooldown:
            return None
        victim: Optional[str] = None
        reason = "idle"
        with self.plane._lock:
            resident = self._resident_locked()
        if self.watermark > 0 and len(resident) > self.watermark:
            victim = self._lru_victim()
            reason = "pressure"
        else:
            idle_candidates = []
            with self.plane._lock:
                for n in resident:
                    s = self.plane._sessions.get(n)
                    if s is None or s._parked is not None or s._cold:
                        continue
                    last = self._last_active.get(n, float("-inf"))
                    if t - last >= self.idle_s and s._inner.pending() == 0:
                        idle_candidates.append((last, n))
            if idle_candidates:
                victim = min(idle_candidates)[1]
        if victim is None:
            return None
        try:
            self.evict(victim, reason=reason)
        except EvictionError:
            self._last_action_t = t
            return None
        except (KeyError, ValueError):
            return None
        self._last_action_t = t
        return "evict"

    # -- observability --------------------------------------------------------

    def _cold_p95_ms(self) -> Optional[float]:
        if not self._cold_starts:
            return None
        xs = sorted(self._cold_starts)
        return xs[min(len(xs) - 1, int(0.95 * (len(xs) - 1) + 0.5))] * 1000.0

    def _status(self) -> Dict[str, Any]:
        plane = self.plane
        with plane._lock:
            resident = len(self._resident_locked())
            evicted = sum(1 for s in plane._sessions.values() if s._cold)
            rows = sum(
                len(s.universe.replica_ids)
                for s in plane.shards
                if s.universe is not None
            )
        docs = resident + evicted
        p95 = self._cold_p95_ms()
        return {
            "plane": plane.name,
            "resident": resident,
            "evicted": evicted,
            "docs": docs,
            "device_rows": rows,
            "tenancy_ratio": round(docs / rows, 3) if rows else None,
            "watermark": self.watermark,
            "idle_s": self.idle_s,
            "cold_start_p95_ms": None if p95 is None else round(p95, 3),
            "last_eviction": self.last_eviction,
            "ticks": self.stats["ticks"],
            "evictions": self.stats["evictions"],
            "hydrations": self.stats["hydrations"],
            "rollbacks": self.stats["rollbacks"],
            "corrupt_fallbacks": self.stats["corrupt_fallbacks"],
            "full_replays": self.stats["full_replays"],
        }

    # -- the loop thread ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self._closed:
            return
        self._thread = threading.Thread(
            target=self._run,
            name=f"peritext-{self.plane.name}-lifecycle",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._closed:
            self._wake.wait(self.interval)
            if self._closed:
                return
            try:
                self.tick()
            except Exception:
                _log.warning(
                    "lifecycle tick failed; the loop survives", exc_info=True
                )

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
