"""Process-wide health plane: circuit-breaker launch gating + recovery.

The chaos plane (runtime/faults.py) made each ingest batch survive a faulty
device launch — retry/backoff/deadline, then oracle degradation.  What it
did NOT bound is the *fleet-level* cost of a backend that stays sick: a
relay wedged for hours (the normal axon failure mode, CLAUDE.md) charges
every subsequent batch the full ``PERITEXT_LAUNCH_RETRIES x
PERITEXT_LAUNCH_TIMEOUT`` budget before degrading.  This module factors the
fast-fail/recover decision out of the per-batch retry policy the same way
Collabs (PAPERS.md) factors delivery resilience out of the CRDT core:

- a :class:`CircuitBreaker` per fault **site** (the same site names the
  chaos plane uses) tracks launch outcomes in a rolling window;
- ``closed -> open`` on a consecutive-failure streak or a failure *rate*
  over the window.  While open, callers skip the whole retry budget and
  **fast-fail** (ingest drops straight into the oracle degrade path at
  degrade-only cost — no retries, no backoff sleeps, no deadline waits);
- ``open -> half_open`` after a jittered cool-down.  Half-open admits
  exactly ONE **canary** launch; concurrent callers keep fast-failing;
- ``half_open -> closed`` when the canary succeeds (the fleet rehydrates
  onto the device fast path), back to ``open`` with a fresh cool-down when
  it fails.

The clock is injectable, so tests drive transitions deterministically
(seeded ``FaultPlan`` ``wedge=TxN`` schedules + a fake clock), and the
cool-down jitter comes from a ``random.Random`` seeded per (plan seed,
site) — two runs of the same schedule open and close at the same instants.

Enable via ``PERITEXT_BREAKER=<spec>`` (the ``PERITEXT_FAULTS`` grammar)
or programmatically::

    PERITEXT_BREAKER="seed=7;device_launch:threshold=3,cooldown=5,jitter=0.2"

    with health.guarded("device_launch:threshold=1,cooldown=0.1"):
        uni.apply_changes(...)

Parameters per site: ``threshold=N`` (consecutive failures to trip;
default 3), ``window=N`` / ``rate=P`` (trip when the last N outcomes are
>= P failures; default 16 / 1.0), ``cooldown=T`` (base cool-down seconds;
default 1.0), ``jitter=P`` (cool-down randomized up to ``+P`` fraction;
default 0.1).

With no plan active every hook returns ``None``/``ALLOW`` at one
dict-lookup cost, so production paths without a breaker stay free.  Every
``CircuitBreaker.stats`` increment mirrors into the telemetry registry as
``health.<site>.<key>`` exactly (tests assert tally equality), fast-fails
additionally bump the aggregate ``health.fastfail`` counter, and every
transition updates the ``health.breaker.state`` /
``health.breaker.<site>.state`` gauges (0 closed, 1 half-open, 2 open).
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from peritext_tpu.runtime import faults, telemetry

# Breaker states (gauge numerics chosen so "bigger = sicker").
CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
_STATE_NUM = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# admit() decisions.
ALLOW = "allow"  # closed: launch normally under the full retry budget
CANARY = "canary"  # half-open: exactly one probe launch, no retries
FASTFAIL = "fastfail"  # open: skip the budget, degrade immediately

_STAT_KEYS = (
    "fastfails",
    "trips",
    "half_opens",
    "closes",
    "canary_failures",
    "successes",
    "failures",
)


class BreakerOpenError(RuntimeError):
    """A launch was fast-failed by an open circuit breaker (no attempt was
    made against the backend; the retry/backoff/timeout budget was not
    charged)."""

    def __init__(self, site: str, remaining: Optional[float] = None):
        msg = f"circuit breaker open for site {site!r}"
        if remaining is not None:
            msg += f" (cool-down: {remaining:.3f}s remaining)"
        super().__init__(msg)
        self.site = site


class CircuitBreaker:
    """One site's breaker state machine (thread-safe; injectable clock)."""

    def __init__(
        self,
        site: str,
        *,
        threshold: int = 3,
        window: int = 16,
        rate: float = 1.0,
        cooldown: float = 1.0,
        jitter: float = 0.1,
        clock: Optional[Callable[[], float]] = None,
        seed: int = 0,
    ) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"rate must be in (0, 1], got {rate}")
        if cooldown < 0 or jitter < 0:
            raise ValueError("cooldown and jitter must be >= 0")
        self.site = site
        self.threshold = threshold
        self.rate = rate
        self.cooldown = cooldown
        self.jitter = jitter
        self._clock = clock if clock is not None else time.monotonic
        self._rng = random.Random(f"{seed}/{site}")
        self._lock = threading.RLock()
        self._window: deque = deque(maxlen=window)
        self._consec = 0
        self._canary_inflight = False
        self._open_until = 0.0
        self.state = CLOSED
        self.stats: Dict[str, int] = {k: 0 for k in _STAT_KEYS}

    # -- bookkeeping (all called under self._lock) ---------------------------

    def _stat(self, key: str, n: int = 1) -> None:
        self.stats[key] += n
        # Mirror exactly into the registry (the faults.py convention:
        # same schedule + call order => same counts on both planes).
        if telemetry.enabled:
            telemetry.counter(f"health.{self.site}.{key}", n)

    def _set_state(self, state: str) -> None:
        self.state = state
        if telemetry.enabled:
            telemetry.gauge(f"health.breaker.{self.site}.state", _STATE_NUM[state])
            telemetry.gauge("health.breaker.state", _STATE_NUM[state])

    def _trip_locked(self) -> Dict[str, Any]:
        # Jittered cool-down: deterministic given the plan seed and the
        # trip sequence (one rng draw per trip).
        span = self.cooldown * (1.0 + self.jitter * self._rng.random())
        self._open_until = self._clock() + span
        self._set_state(OPEN)
        # A trip is the definitional post-mortem moment: the backend just
        # crossed from "flaky" to "sick".  The ring event lands here (O(1)
        # under our lock); the black-box dump — snapshot + file I/O — is
        # returned to the caller to run AFTER the lock releases, so a slow
        # disk cannot stall every concurrent admit()/record on this site
        # behind the post-mortem write.
        if telemetry.enabled:
            telemetry.record(
                "health.trip", outcome="open", breaker=self.site, cooldown_s=span
            )
        return {
            "site": self.site,
            "cooldown_s": span,
            "consecutive_failures": self._consec,
            "stats": dict(self.stats),
        }

    def _should_trip_locked(self) -> bool:
        if self._consec >= self.threshold:
            return True
        if len(self._window) == self._window.maxlen:
            fails = sum(1 for ok in self._window if not ok)
            return fails / len(self._window) >= self.rate
        return False

    # -- the caller-facing protocol ------------------------------------------

    def admit(self) -> str:
        """Gate one launch: ALLOW (closed), CANARY (half-open probe — granted
        to exactly one caller per half-open period), or FASTFAIL (open, or a
        canary is already in flight)."""
        with self._lock:
            if self.state == OPEN:
                if self._clock() >= self._open_until:
                    self._stat("half_opens")
                    self._set_state(HALF_OPEN)
                else:
                    self._stat("fastfails")
                    if telemetry.enabled:
                        telemetry.counter("health.fastfail")
                        telemetry.record(
                            "health.fastfail", outcome="open", breaker=self.site
                        )
                    return FASTFAIL
            if self.state == HALF_OPEN:
                if self._canary_inflight:
                    self._stat("fastfails")
                    if telemetry.enabled:
                        telemetry.counter("health.fastfail")
                        telemetry.record(
                            "health.fastfail", outcome="canary_inflight", breaker=self.site
                        )
                    return FASTFAIL
                self._canary_inflight = True
                return CANARY
            return ALLOW

    def record_success(self) -> None:
        """A launch completed (readback-verified where the caller does so).
        Closes the breaker when this was the half-open canary."""
        with self._lock:
            self._stat("successes")
            self._consec = 0
            self._window.append(True)
            self._canary_inflight = False
            if self.state == HALF_OPEN:
                # Recovery: the rolling history predates the outage and must
                # not re-trip the fresh circuit.
                self._window.clear()
                self._stat("closes")
                self._set_state(CLOSED)

    def record_failure(self) -> None:
        """A launch attempt failed with a transient (retryable) error."""
        dump_info: Optional[Dict[str, Any]] = None
        with self._lock:
            self._stat("failures")
            self._consec += 1
            self._window.append(False)
            if self.state == HALF_OPEN:
                # The canary failed: back to open with a fresh cool-down.
                self._canary_inflight = False
                self._stat("canary_failures")
                dump_info = self._trip_locked()
            elif self.state == CLOSED and self._should_trip_locked():
                self._stat("trips")
                dump_info = self._trip_locked()
        if dump_info is not None:
            # Post-mortem dump outside the breaker lock (no-op unless
            # PERITEXT_BLACKBOX is armed; names the tripped site).  The
            # dedupe key is per site: a trip storm on one site writes one
            # dump per cooldown, without suppressing another site's first
            # trip (the ISSUE 13 shared-cooldown rule).
            telemetry.blackbox_dump(
                "breaker_trip",
                dedupe_key=f"breaker_trip:{self.site}",
                **dump_info,
            )

    def abandon(self) -> None:
        """Release a canary slot without recording an outcome (the launch
        died on a SEMANTIC error — no evidence about backend health)."""
        with self._lock:
            self._canary_inflight = False

    def cooldown_remaining(self) -> float:
        """Seconds until an open breaker will half-open (0 when not open)."""
        with self._lock:
            if self.state != OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())

    def set_param(self, action: str, value: str) -> None:
        """Apply one spec ``action=value`` pair (PERITEXT_BREAKER grammar)."""
        if action == "threshold":
            self.threshold = int(value)
            if self.threshold < 1:
                raise ValueError(f"threshold must be >= 1, got {value}")
        elif action == "window":
            n = int(value)
            if n < 1:
                raise ValueError(f"window must be >= 1, got {value}")
            self._window = deque(self._window, maxlen=n)
        elif action == "rate":
            self.rate = float(value)
            if not 0.0 < self.rate <= 1.0:
                raise ValueError(f"rate must be in (0, 1], got {value}")
        elif action == "cooldown":
            self.cooldown = float(value)
            if self.cooldown < 0:
                raise ValueError(f"cooldown must be >= 0, got {value}")
        elif action == "jitter":
            self.jitter = float(value)
            if self.jitter < 0:
                raise ValueError(f"jitter must be >= 0, got {value}")
        else:
            raise ValueError(f"unknown breaker parameter {action!r}")

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"state": self.state}
            out.update(self.stats)
            return out


class HealthPlan:
    """A set of per-site breakers (the health-plane analog of FaultPlan)."""

    def __init__(self, seed: int = 0, clock: Optional[Callable[[], float]] = None) -> None:
        self.seed = seed
        self.clock = clock
        self._breakers: Dict[str, CircuitBreaker] = {}

    def site(self, name: str, **params: Any) -> CircuitBreaker:
        if name not in faults.KNOWN_SITES:
            # Same rationale as FaultPlan.site: a typo'd site would gate
            # nothing and let a resilience test pass vacuously.
            raise ValueError(
                f"unknown breaker site {name!r}; known sites: "
                f"{', '.join(faults.KNOWN_SITES)}"
            )
        br = self._breakers.get(name)
        if br is None:
            br = self._breakers[name] = CircuitBreaker(
                name, clock=self.clock, seed=self.seed
            )
        for action, value in params.items():
            br.set_param(action, str(value))
        return br

    def breaker(self, name: str) -> Optional[CircuitBreaker]:
        return self._breakers.get(name)

    @classmethod
    def from_spec(
        cls,
        spec: str,
        seed: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> "HealthPlan":
        """Parse the ``PERITEXT_BREAKER`` grammar (same shape as
        ``PERITEXT_FAULTS``: ``seed=N`` clauses and
        ``site:param=value[,param=value...]`` clauses, ``;``-separated)."""
        plan = cls(seed=seed if seed is not None else 0, clock=clock)
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed=") and ":" not in clause:
                if seed is None:
                    plan.seed = int(clause[5:])
                continue
            site_name, sep, actions = clause.partition(":")
            if not sep or not actions:
                raise ValueError(
                    f"bad breaker clause {clause!r} (want site:param=value[,...])"
                )
            br = plan.site(site_name.strip())
            for part in actions.split(","):
                action, sep, value = part.partition("=")
                if not sep:
                    raise ValueError(
                        f"bad breaker parameter {part!r} in clause {clause!r}"
                    )
                br.set_param(action.strip(), value.strip())
        # Re-seed every breaker with the final plan seed (a ``seed=N``
        # clause may appear after a site clause; jitter must not depend on
        # clause order).
        for br in plan._breakers.values():
            br._rng = random.Random(f"{plan.seed}/{br.site}")
        return plan

    def summary(self) -> Dict[str, Any]:
        return {name: br.summary() for name, br in self._breakers.items()}


# -- the process-wide plan ---------------------------------------------------

_installed: Optional[HealthPlan] = None
_env_plan: Optional[HealthPlan] = None
_env_spec: Optional[str] = None


def active() -> Optional[HealthPlan]:
    """The active plan: an installed one, else one parsed from
    ``PERITEXT_BREAKER`` (re-parsed with fresh state if the spec changes)."""
    global _env_plan, _env_spec
    if _installed is not None:
        return _installed
    spec = os.environ.get("PERITEXT_BREAKER")
    if not spec:
        return None
    if spec != _env_spec:
        # Parse BEFORE caching the spec: a malformed spec must raise on
        # EVERY use, not once — otherwise the breaker silently gates
        # nothing for the rest of the process (the vacuous-pass mode
        # plan.site() exists to prevent).
        _env_plan = HealthPlan.from_spec(spec)
        _env_spec = spec
    return _env_plan


def install(plan: "HealthPlan | str") -> HealthPlan:
    """Install a plan process-wide (overrides any ``PERITEXT_BREAKER`` env)."""
    global _installed
    if isinstance(plan, str):
        plan = HealthPlan.from_spec(plan)
    _installed = plan
    return plan


def reset() -> None:
    """Remove any installed plan and forget the env-parsed one (a spec still
    in the env re-parses with pristine breakers on next use)."""
    global _installed, _env_plan, _env_spec
    _installed = None
    _env_plan = None
    _env_spec = None


@contextlib.contextmanager
def guarded(plan: "HealthPlan | str"):
    """Scoped installation: ``with health.guarded("device_launch:threshold=1"):``."""
    global _installed
    prev = _installed
    current = install(plan)
    try:
        yield current
    finally:
        _installed = prev


def breaker(site: str) -> Optional[CircuitBreaker]:
    """The active breaker for a site, or None (the common no-plan case)."""
    plan = active()
    if plan is None:
        return None
    return plan.breaker(site)


def summary() -> Dict[str, Any]:
    """Per-site breaker state + tallies for bench lines and chaos footers
    (empty when no plan is active — callers stamp it only when non-empty)."""
    plan = active()
    if plan is None:
        return {}
    return plan.summary()
