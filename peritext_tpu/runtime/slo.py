"""Process-wide SLO plane: declarative objectives + burn-rate breach alerts.

The observability stack (telemetry.py) can *reconstruct* latency and error
behavior offline — ``obs.summary()`` estimates e2e percentiles from the
log2 histograms, ``scripts/trace_report.py`` re-derives them from a trace
— but nothing in the process could say "the p95 admit-to-applied objective
is being violated *right now*".  This module is that online judgment: a
seeded :class:`SloPlan` holds one sliding-window evaluator per named
**objective**, fed live from the existing telemetry sites (no new
instrumentation — the evaluators subscribe to :func:`telemetry.observe` /
:func:`telemetry.counter` names through sink maps), with multi-window
burn-rate breach detection in the Google-SRE shape: a breach requires the
**fast** window (recent events) AND the **slow** window (the full window)
to both burn error budget faster than the threshold, so a single slow
launch cannot page while a sustained regression fires within a handful of
events.

Objective kinds (inferred from the clause's parameters):

- **latency**: ``pNN=<ms>`` targets against a stream of observed seconds —
  any histogram name fed through ``telemetry.observe`` (``e2e.*``,
  ``span.<name>.seconds``, ``serve.flush_seconds``, ...).  The error
  budget for ``p95=50`` is 5% of events over 50ms; the burn rate is the
  observed over-target fraction divided by that budget.
- **error rate**: ``err_rate=<P>`` against a pair of counters — by
  convention ``<name>_attempts`` (events) and ``<name>_failures``
  (errors), which is exactly how the ingest launch path already counts
  (``ingest.launch_attempts`` / ``ingest.launch_failures``); override
  with ``total=<counter>,errors=<counter>`` for pairs that don't follow
  the convention (e.g. ``total=serve.flushes,errors=serve.flush_failures``).

Spec grammar (the ``PERITEXT_FAULTS`` shape, ``;``-separated clauses)::

    PERITEXT_SLO="seed=0;e2e.admit_to_applied:p95=50,window=256;\
ingest.launch:err_rate=0.01,window=128"

Per-clause parameters: ``window=N`` (sliding event window, default 128),
``fast=N`` (fast-window length, default ``max(8, window // 8)``),
``burn=X`` (burn-rate threshold both windows must reach, default 1.0),
``min=N`` (events required before a verdict, default the fast length),
``cooldown=T`` (black-box dump rate limit per objective, seconds, default
60; judged on the plan's injectable clock, so chaos tests drive it
deterministically).

Evaluation is **deterministic given the event order**: no wall-clock
enters a verdict (the clock only rate-limits dumps), so a seeded chaos
run breaches at exactly the same event on every run.  On a breach
transition the objective increments ``slo.<name>.breach``, sets the
``slo.<name>.breached`` gauge, records a flight-recorder event, and fires
a rate-limited black-box dump naming the objective; recovery clears the
gauge.  The live ``slo.<name>.burn`` / ``slo.<name>.compliance`` gauges
ride :func:`telemetry.summary` (bench JSON stamps, the fuzz ``--chaos``
footer) and :func:`telemetry.status` (the ops surface), and the breach
state feeds tail-sampled tracing's ``breach`` rule through the installed
probe.

With no plan installed, the fed sites cost one module-attribute load and
a ``None`` check on top of the normal enabled-path work — the disabled
path (telemetry off) is unchanged at one attribute check
(tests/test_telemetry.py pins it).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from peritext_tpu.runtime import telemetry

_DEF_WINDOW = 128
_DEF_BURN = 1.0
_DEF_COOLDOWN = 60.0


class SloObjective:
    """One objective's sliding-window evaluator (thread-safe; the feed
    sites may fire from scheduler threads and foreground ingest at once)."""

    def __init__(
        self, name: str, clock: Optional[Callable[[], float]] = None
    ) -> None:
        self.name = name
        self.window = _DEF_WINDOW
        self.fast: Optional[int] = None  # default resolves from window
        self.burn_threshold = _DEF_BURN
        self.min_events: Optional[int] = None  # default resolves to fast
        self.cooldown = _DEF_COOLDOWN
        # Latency targets: quantile key ("p95") -> threshold seconds.
        self.latency_targets: Dict[str, float] = {}
        self.err_rate: Optional[float] = None
        self.total_counter: Optional[str] = None
        self.error_counter: Optional[str] = None
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        # The event window.  Latency: observed seconds (floats).  Error
        # rate: per-event badness (bools).
        self._vals: deque = deque()
        # Per-target over-budget counts across the SLOW (full) window,
        # maintained incrementally so evaluation is O(fast + targets),
        # not O(window).
        self._slow_bad: Dict[str, int] = {}
        self.events = 0  # total events ever fed (monotonic)
        self.burn = 0.0
        self.compliance = 1.0
        self.breached = False
        self.breaches = 0
        self._last_dump: Optional[float] = None

    # -- construction --------------------------------------------------------

    def set_param(self, action: str, value: str) -> None:
        """Apply one spec ``param=value`` pair (PERITEXT_SLO grammar)."""
        if action.startswith("p") and action[1:].replace(".", "").isdigit():
            q = float(action[1:]) / 100.0
            if not 0.0 < q < 1.0:
                raise ValueError(f"quantile target {action!r} out of (p0, p100)")
            self.latency_targets[action] = float(value) / 1000.0  # ms -> s
        elif action == "err_rate":
            self.err_rate = float(value)
            if not 0.0 < self.err_rate <= 1.0:
                raise ValueError(f"err_rate must be in (0, 1], got {value}")
        elif action == "window":
            self.window = int(value)
            if self.window < 2:
                raise ValueError(f"window must be >= 2, got {value}")
        elif action == "fast":
            self.fast = int(value)
            if self.fast < 1:
                raise ValueError(f"fast must be >= 1, got {value}")
        elif action == "burn":
            self.burn_threshold = float(value)
            if self.burn_threshold <= 0:
                raise ValueError(f"burn must be > 0, got {value}")
        elif action == "min":
            self.min_events = int(value)
        elif action == "cooldown":
            self.cooldown = float(value)
            if self.cooldown < 0:
                raise ValueError(f"cooldown must be >= 0, got {value}")
        elif action == "total":
            self.total_counter = value
        elif action == "errors":
            self.error_counter = value
        else:
            raise ValueError(
                f"unknown SLO parameter {action!r} for objective {self.name!r}"
            )

    def validate(self) -> None:
        if bool(self.latency_targets) == (self.err_rate is not None):
            raise ValueError(
                f"objective {self.name!r} needs exactly one of pNN=<ms> "
                "latency targets or err_rate=<P>"
            )

    def _fast_n(self) -> int:
        return self.fast if self.fast is not None else max(8, self.window // 8)

    def _min_n(self) -> int:
        return self.min_events if self.min_events is not None else self._fast_n()

    def _budgets(self) -> Dict[str, float]:
        """Per-target error budgets: the allowed bad-event fraction."""
        if self.err_rate is not None:
            return {"err": self.err_rate}
        return {
            key: 1.0 - float(key[1:]) / 100.0
            for key in self.latency_targets
        }

    def _bad(self, key: str, value: Any) -> bool:
        if self.err_rate is not None:
            return bool(value)
        return value > self.latency_targets[key]

    # -- the event feed ------------------------------------------------------

    def _append_locked(self, value: Any) -> None:
        self._vals.append(value)
        self.events += 1
        for key in self._budgets():
            if self._bad(key, value):
                self._slow_bad[key] = self._slow_bad.get(key, 0) + 1
        while len(self._vals) > self.window:
            old = self._vals.popleft()
            for key in self._budgets():
                if self._bad(key, old):
                    self._slow_bad[key] -= 1

    def feed_value(self, value: float) -> None:
        """One latency observation (seconds — the telemetry.observe unit)."""
        with self._lock:
            self._append_locked(value)
            dump = self._evaluate_locked()
        self._fire(dump)

    def feed_total(self, n: int = 1) -> None:
        """``n`` events (the total/attempts counter incremented)."""
        with self._lock:
            for _ in range(n):
                self._append_locked(False)
            dump = self._evaluate_locked()
        self._fire(dump)

    def feed_errors(self, n: int = 1) -> None:
        """``n`` of the recent events failed (the errors counter).  The
        instrumented convention counts the attempt first and the failure
        after it lands, so errors flip the most recent still-ok events;
        an error with no matching attempt (defensive) appends."""
        with self._lock:
            flipped = 0
            idx = len(self._vals) - 1
            while idx >= 0 and flipped < n:
                if self._vals[idx] is False:
                    self._vals[idx] = True
                    self._slow_bad["err"] = self._slow_bad.get("err", 0) + 1
                    flipped += 1
                idx -= 1
            for _ in range(n - flipped):
                self._append_locked(True)
            dump = self._evaluate_locked()
        self._fire(dump)

    # -- evaluation ----------------------------------------------------------

    def _evaluate_locked(self) -> Optional[Dict[str, Any]]:
        """Re-judge the objective after one event; returns black-box dump
        info to fire OUTSIDE the lock (file I/O must not serialize the
        feed sites), or None."""
        n = len(self._vals)
        if n == 0:
            return None
        fast_n = min(n, self._fast_n())
        budgets = self._budgets()
        burn = 0.0
        burn_exit = 0.0
        worst_slow_frac = 0.0
        for key, budget in budgets.items():
            slow_frac = self._slow_bad.get(key, 0) / n
            fast_bad = 0
            for i in range(fast_n):  # fast window: the most recent events
                if self._bad(key, self._vals[-1 - i]):
                    fast_bad += 1
            fast_frac = fast_bad / fast_n
            # Multi-window rule: the target's effective burn is the LOWER
            # of its fast/slow burns — both windows must burn for a
            # breach, so a lone outlier (fast spikes, slow doesn't) and a
            # stale streak aging out (slow high, fast recovered) both
            # stay quiet.  Recovery is judged on the HIGHER of the two
            # (hysteresis): an ongoing storm whose windows momentarily
            # disagree event-to-event must not flap the breach state.
            burn = max(burn, min(fast_frac, slow_frac) / budget)
            burn_exit = max(burn_exit, max(fast_frac, slow_frac) / budget)
            worst_slow_frac = max(worst_slow_frac, slow_frac)
        self.burn = burn
        self.compliance = 1.0 - worst_slow_frac
        if telemetry.enabled:
            telemetry.gauge(f"slo.{self.name}.burn", burn)
            telemetry.gauge(f"slo.{self.name}.compliance", self.compliance)
        if self.breached:
            breached_now = burn_exit >= self.burn_threshold
        else:
            breached_now = n >= self._min_n() and burn >= self.burn_threshold
        if breached_now and not self.breached:
            self.breached = True
            self.breaches += 1
            if telemetry.enabled:
                telemetry.counter(f"slo.{self.name}.breach")
                telemetry.gauge(f"slo.{self.name}.breached", 1)
                telemetry.record(
                    "slo.breach", outcome="breach", slo=self.name, burn=burn
                )
            now = self._clock()
            if self._last_dump is None or now - self._last_dump >= self.cooldown:
                self._last_dump = now
                return {
                    "slo": self.name,
                    "burn": burn,
                    "compliance": self.compliance,
                    "events": self.events,
                    "breaches": self.breaches,
                    "objective": self.describe(),
                }
            if telemetry.enabled:
                telemetry.counter(f"slo.{self.name}.dump_suppressed")
        elif not breached_now and self.breached:
            self.breached = False
            if telemetry.enabled:
                telemetry.gauge(f"slo.{self.name}.breached", 0)
                telemetry.record(
                    "slo.breach", outcome="recovered", slo=self.name, burn=burn
                )
        return None

    def _fire(self, dump: Optional[Dict[str, Any]]) -> None:
        if dump is not None:
            # The objective already rate-limited on its own (injectable)
            # clock — dedupe_cooldown_s=0 bypasses the wall-clock limiter
            # so a fake-clock chaos test still sees its dump; the per-SLO
            # dedupe key keeps distinct objectives independent.
            telemetry.blackbox_dump(
                "slo_breach",
                dedupe_key=f"slo_breach:{self.name}",
                dedupe_cooldown_s=0.0,
                **dump,
            )

    # -- introspection -------------------------------------------------------

    def describe(self) -> str:
        if self.err_rate is not None:
            return f"err_rate<={self.err_rate:g}"
        return ",".join(
            f"{k}<={t * 1000:g}ms" for k, t in sorted(self.latency_targets.items())
        )

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "objective": self.describe(),
                "window": self.window,
                "fast": self._fast_n(),
                "burn_threshold": self.burn_threshold,
                "events": self.events,
                "burn": round(self.burn, 4),
                "compliance": round(self.compliance, 4),
                "breached": self.breached,
                "breaches": self.breaches,
            }


class SloPlan:
    """A set of objectives (the SLO analog of FaultPlan/HealthPlan).  The
    ``seed`` clause is accepted for grammar symmetry and recorded; the
    evaluators themselves are deterministic in event order and draw no
    randomness."""

    def __init__(
        self, seed: int = 0, clock: Optional[Callable[[], float]] = None
    ) -> None:
        self.seed = seed
        self.clock = clock
        self._objectives: Dict[str, SloObjective] = {}

    def objective(self, name: str, **params: Any) -> SloObjective:
        obj = self._objectives.get(name)
        if obj is None:
            obj = self._objectives[name] = SloObjective(name, clock=self.clock)
        for action, value in params.items():
            obj.set_param(action, str(value))
        return obj

    def objectives(self) -> List[SloObjective]:
        return list(self._objectives.values())

    @classmethod
    def from_spec(
        cls,
        spec: str,
        seed: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> "SloPlan":
        """Parse the ``PERITEXT_SLO`` grammar (the ``PERITEXT_FAULTS``
        shape: ``seed=N`` clauses and ``name:param=value[,...]`` clauses,
        ``;``-separated)."""
        plan = cls(seed=seed if seed is not None else 0, clock=clock)
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed=") and ":" not in clause:
                if seed is None:
                    plan.seed = int(clause[5:])
                continue
            name, sep, params = clause.partition(":")
            if not sep or not params:
                raise ValueError(
                    f"bad SLO clause {clause!r} (want name:param=value[,...])"
                )
            obj = plan.objective(name.strip())
            for part in params.split(","):
                action, sep, value = part.partition("=")
                if not sep:
                    raise ValueError(
                        f"bad SLO parameter {part!r} in clause {clause!r}"
                    )
                obj.set_param(action.strip(), value.strip())
        for obj in plan._objectives.values():
            obj.validate()
        return plan

    # -- wiring --------------------------------------------------------------

    def sinks(
        self,
    ) -> Tuple[Dict[str, Callable[[float], None]], Dict[str, Callable[[int], None]]]:
        """(observe-name -> feed, counter-name -> feed) maps for
        :func:`telemetry._install_slo_sinks`."""
        observe_map: Dict[str, Callable[[float], None]] = {}
        counter_map: Dict[str, Callable[[int], None]] = {}
        for obj in self._objectives.values():
            if obj.err_rate is not None:
                total = obj.total_counter or obj.name + "_attempts"
                errors = obj.error_counter or obj.name + "_failures"
                counter_map[total] = obj.feed_total
                counter_map[errors] = obj.feed_errors
            else:
                observe_map[obj.name] = obj.feed_value
        return observe_map, counter_map

    def breach_active(self) -> bool:
        """True while any objective is in breach — the tail-sampled
        tracer's ``breach`` retention probe."""
        return any(obj.breached for obj in self._objectives.values())

    def summary(self) -> Dict[str, Any]:
        return {name: obj.summary() for name, obj in self._objectives.items()}


# -- the process-wide plan ----------------------------------------------------

_installed: Optional[SloPlan] = None
_env_plan: Optional[SloPlan] = None
_env_spec: Optional[str] = None


def _wire(plan: Optional[SloPlan]) -> None:
    if plan is None:
        telemetry._install_slo_sinks(None, None, None)
        return
    observe_map, counter_map = plan.sinks()
    telemetry._install_slo_sinks(observe_map, counter_map, plan.breach_active)


def active() -> Optional[SloPlan]:
    """The active plan: an installed one, else one parsed (and wired) from
    ``PERITEXT_SLO`` (re-parsed with fresh evaluators if the spec
    changes)."""
    global _env_plan, _env_spec
    if _installed is not None:
        return _installed
    spec = os.environ.get("PERITEXT_SLO")
    if not spec:
        return None
    if spec != _env_spec:
        # Parse BEFORE caching the spec: a malformed spec must raise on
        # every use, not once-then-silently-judge-nothing.
        _env_plan = SloPlan.from_spec(spec)
        _env_spec = spec
        _wire(_env_plan)
    return _env_plan


def install(plan: "SloPlan | str") -> SloPlan:
    """Install a plan process-wide (overrides any ``PERITEXT_SLO`` env)
    and wire its feed sinks into the telemetry plane.  Objectives only
    evaluate while collection is on — callers enable telemetry (env
    activation does both)."""
    global _installed
    if isinstance(plan, str):
        plan = SloPlan.from_spec(plan)
    _installed = plan
    _wire(plan)
    return plan


def reset() -> None:
    """Remove any installed plan, forget the env-parsed one, and clear the
    telemetry sinks (a spec still in the env re-parses with fresh
    evaluators on next use)."""
    global _installed, _env_plan, _env_spec
    _installed = None
    _env_plan = None
    _env_spec = None
    _wire(None)


@contextlib.contextmanager
def guarded(plan: "SloPlan | str"):
    """Scoped installation:
    ``with slo.guarded("ingest.launch:err_rate=0.1"):``."""
    global _installed
    prev = _installed
    current = install(plan)
    try:
        yield current
    finally:
        _installed = prev
        # Re-wire whatever is active now: the previous installed plan, or
        # — when none — the cached env plan (active() returns it without
        # re-wiring, so wiring `prev` alone would permanently disconnect
        # a PERITEXT_SLO env plan's sinks while summary() kept showing
        # its frozen objectives).
        _wire(prev if prev is not None else active())


def summary() -> Dict[str, Any]:
    """Per-objective verdicts for bench stamps, chaos footers, and the
    status surface (empty when no plan is active)."""
    plan = active()
    if plan is None:
        return {}
    return plan.summary()


def _activate_from_env() -> None:
    """Import-time activation: a ``PERITEXT_SLO`` spec in the environment
    wires its sinks and turns collection on (an objective that never sees
    events because telemetry stayed off would judge nothing, vacuously)."""
    if os.environ.get("PERITEXT_SLO"):
        active()  # parses + wires (raises loudly on a malformed spec)
        telemetry.enable()


_activate_from_env()
