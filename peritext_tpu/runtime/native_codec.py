"""ctypes binding for the native change-log codec (native/peritext_native.cpp).

Columnar zigzag+delta+LEB128 varint coding of int32 matrices — the encoded
form of op-row tensors and change batches for log shipping and durable
storage.  Builds the shared library on first use if g++ is available;
otherwise a pure-Python fallback provides the identical format (the two are
differential-tested against each other in tests/test_native_codec.py).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libperitext_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.pt_encode_bound.restype = ctypes.c_size_t
    lib.pt_encode_bound.argtypes = [ctypes.c_size_t]
    lib.pt_encode_columns.restype = ctypes.c_size_t
    lib.pt_encode_columns.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_size_t,
    ]
    lib.pt_decode_columns.restype = ctypes.c_size_t
    lib.pt_decode_columns.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_size_t,
    ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_library() is not None


# -- pure-Python reference implementation (same format) ----------------------


def _zigzag(v: np.ndarray) -> np.ndarray:
    v = v.astype(np.int64)
    return ((v << 1) ^ (v >> 31)).astype(np.uint32) & np.uint32(0xFFFFFFFF)


def _py_encode(columns: np.ndarray) -> bytes:
    out = bytearray()
    for col in columns:
        deltas = np.diff(col.astype(np.int64), prepend=np.int64(0)).astype(np.int32)
        for z in _zigzag(deltas):
            z = int(z)
            while z >= 0x80:
                out.append((z & 0x7F) | 0x80)
                z >>= 7
            out.append(z)
    return bytes(out)


def _py_decode(data: bytes, n_cols: int, n_rows: int) -> np.ndarray:
    out = np.empty((n_cols, n_rows), np.int32)
    pos = 0
    for c in range(n_cols):
        prev = 0
        for r in range(n_rows):
            result = 0
            shift = 0
            while True:
                if pos >= len(data) or shift >= 35:
                    raise ValueError("malformed varint stream")
                b = data[pos]
                pos += 1
                # Mask to 32 bits so non-canonical 5-byte varints decode
                # identically to the native path (which ORs into uint32).
                result = (result | ((b & 0x7F) << shift)) & 0xFFFFFFFF
                if not b & 0x80:
                    break
                shift += 7
            delta = (result >> 1) ^ -(result & 1)
            prev = (prev + delta) & 0xFFFFFFFF
            if prev >= 0x80000000:
                prev -= 0x100000000
            out[c, r] = prev
    if pos != len(data):
        raise ValueError("trailing bytes in varint stream")
    return out


# -- public API --------------------------------------------------------------


def encode_columns(matrix: np.ndarray, force_python: bool = False) -> bytes:
    """Encode an int32 [n_cols, n_rows] matrix (column-major semantics)."""
    matrix = np.ascontiguousarray(matrix, np.int32)
    if matrix.ndim != 2:
        raise ValueError("expected a 2-D matrix")
    lib = None if force_python else _load_library()
    if lib is None:
        return _py_encode(matrix)
    n_cols, n_rows = matrix.shape
    bound = lib.pt_encode_bound(matrix.size)
    out = np.empty(max(bound, 1), np.uint8)
    written = lib.pt_encode_columns(
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_cols,
        n_rows,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.size,
    )
    if written == 0 and matrix.size > 0:
        raise RuntimeError("native encode failed")
    return out[:written].tobytes()


def decode_columns(
    data: bytes, n_cols: int, n_rows: int, force_python: bool = False
) -> np.ndarray:
    """Decode to an int32 [n_cols, n_rows] matrix."""
    if n_cols * n_rows == 0:
        if data:
            raise ValueError("trailing bytes in varint stream")
        return np.empty((n_cols, n_rows), np.int32)
    lib = None if force_python else _load_library()
    if lib is None:
        return _py_decode(data, n_cols, n_rows)
    buf = np.frombuffer(data, np.uint8)
    out = np.empty((n_cols, n_rows), np.int32)
    got = lib.pt_decode_columns(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        buf.size,
        n_cols,
        n_rows,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.size,
    )
    if got != n_cols * n_rows:
        raise ValueError("malformed varint stream")
    return out
