"""Process-wide telemetry plane: metrics registry + span tracer.

Until this module existed the only windows into runtime behavior were the
ad-hoc ``TpuUniverse.stats`` dict, ``FaultPlan.stats`` on the chaos plane,
and the one-line bench JSON — questions like "how many launches retried",
"which merge path actually ran", or "did the cohort pipeline overlap" took
printf archaeology.  This module is the shared substrate every layer
reports through:

- a **metrics registry**: monotonic counters, last/max gauges, and
  histograms with fixed log2 buckets (64 buckets; bucket *i* counts values
  in ``[2**(i-33), 2**(i-32))``, exact over ``[2**-32, 2**30)`` — sub-ns
  to ~34-year durations — with explicit ``"<=-32"`` / ``">=31"`` overflow
  buckets at the ends; larger magnitudes belong in counters);
- a **span tracer**: ``with telemetry.span("ingest.launch"): ...`` records
  a Chrome trace-event-format *complete event* (``"ph": "X"``) with
  monotonic microsecond timestamps and the recording thread's id, so
  nested spans render as flame stacks per thread in Perfetto /
  chrome://tracing.  Every span also lands in the registry as a
  ``span.<name>.seconds`` histogram;
- a **causal flow plane**: a :class:`TraceContext` (monotonic id — no
  wall-clock entropy) is minted where a change enters the system
  (``ChangeQueue.enqueue``, ``TpuDoc.change``, ``Publisher.publish``,
  cohort launch) and threaded through every seam it crosses, emitting
  Chrome *flow events* (``ph: s/t/f``) bound to the enclosing span, so
  Perfetto draws one arrow-connected lane per change across threads.
  The terminal seam feeds end-to-end latency histograms
  (``e2e.enqueue_to_applied``, ``e2e.publish_to_delivered``, ...).
  Propagation is thread-local (:func:`flowing` / :func:`current_flows`)
  so deep seams (ingest retries, degradation, readback) join the lane
  without threading a context argument through every signature;
- a **flight recorder**: a fixed-capacity ring of recent structured
  events (site, flow id, outcome, µs) that is always recording while
  telemetry is enabled.  On a failure worth a post-mortem (launch-budget
  exhaustion, breaker trip, checkpoint corruption, unhandled ingest
  exception) :func:`blackbox_dump` writes the ring + a registry snapshot
  to ``PERITEXT_BLACKBOX=<dir>`` — the post-mortem for the wedged-relay
  failure mode where the atexit-only dump dies with the process.

Activation
==========

``PERITEXT_TRACE=<path>`` writes trace events as JSONL (one JSON object
per line; wrap with ``jq -s . trace.jsonl > trace.json`` for
chrome://tracing — Perfetto's importer reads the newline-delimited form
directly).  ``PERITEXT_METRICS=<path>`` dumps a JSON metrics snapshot at
interpreter exit; ``PERITEXT_METRICS_INTERVAL=<secs>`` additionally
flushes that snapshot periodically from a daemon thread (atomic
tmp+rename), so a SIGKILLed/timed-out child leaves a recent snapshot
instead of nothing.  ``PERITEXT_BLACKBOX=<dir>`` arms the flight
recorder's failure dumps (``PERITEXT_BLACKBOX_RING`` sizes the ring,
default 512 events).  Any of these env vars enables collection at
import; tests and embedders call :func:`enable` / :func:`disable` /
:func:`reset` programmatically.

The overhead contract
=====================

Instrumented call sites sit inside the ingest hot loop, so the DISABLED
path must be near-free: every site guards on the single module attribute
:data:`enabled` —

    if telemetry.enabled:
        telemetry.counter("ingest.launch_retries")

— one attribute check, no call, no allocation, no lock taken.  (The
module-level helpers also re-check ``enabled`` internally, so unguarded
sites are merely slower, never wrong.)  ``span()`` when disabled returns a
shared no-op singleton, so even unguarded ``with telemetry.span(...)``
allocates nothing.  tests/test_telemetry.py pins both properties.

Enabled, the cost is one small dict update under a lock per event —
instrumentation is launch-level (per kernel launch / flush / cohort),
never per-op, so a telemetry-on run stays within a couple percent of
telemetry-off on the patched-fleet steady state.

Thread safety: all registry mutation happens under one lock (concurrent
``ChangeQueue`` timer flushes and foreground ingest cannot lose
increments), and each ``span()`` call returns a fresh span object, so
nested or cross-thread spans cannot corrupt one another.
"""
from __future__ import annotations

import atexit
import itertools
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# NOTE: `enabled` is deliberately NOT in __all__ — `from telemetry import
# enabled` would snapshot the flag at import time and make guards
# permanently dead.  The one correct spelling is the attribute form the
# docstring prescribes: `telemetry.enabled`.
__all__ = [
    "enable",
    "disable",
    "reset",
    "counter",
    "gauge",
    "gauge_max",
    "observe",
    "span",
    "snapshot",
    "summary",
    "dump_metrics",
    "flush_trace",
    "trace_path",
    "TraceContext",
    "flow",
    "flow_point",
    "flow_steps",
    "flowing",
    "current_flows",
    "current_flow",
    "flow_elapsed_s",
    "record",
    "recorder_events",
    "recorder_stats",
    "blackbox_dir",
    "blackbox_dump",
    "estimate_quantiles",
]

# THE hot-path gate (see the overhead contract above).
enabled = False

_N_BUCKETS = 64
_BUCKET_OFFSET = 32  # bucket i counts values v with frexp(v)[1] == i - 32


class _Histogram:
    """Fixed-log2-bucket histogram (+ count/sum/min/max)."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets = [0] * _N_BUCKETS

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if value > 0:
            i = min(_N_BUCKETS - 1, max(0, math.frexp(value)[1] + _BUCKET_OFFSET))
        else:
            i = 0  # non-positive values share the smallest bucket
        self.buckets[i] += 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            # Keyed by the bucket's upper-bound exponent: a value v landed
            # in bucket "e" iff 2**(e-1) <= v < 2**e.  The clamped end
            # buckets say so explicitly — "<=-32" holds everything below
            # 2**-32 (including non-positive values), ">=31" everything
            # from 2**30 up — so a snapshot can never silently claim an
            # out-of-range value sat inside a nominal bucket.
            "buckets": {
                (
                    "<=-32"
                    if i == 0
                    else ">=31"
                    if i == _N_BUCKETS - 1
                    else str(i - _BUCKET_OFFSET)
                ): c
                for i, c in enumerate(self.buckets)
                if c
            },
        }


def estimate_quantiles(
    hist_json: Dict[str, Any], qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> Optional[Dict[str, float]]:
    """p-quantile estimates from a snapshot-format log2-bucket histogram.

    Works on the JSON form (so dumped snapshots and live ones estimate
    identically): each nominal bucket "e" holds values in [2**(e-1), 2**e);
    the estimate is the bucket's geometric midpoint, clamped to the
    histogram's observed [min, max].  The clamped end buckets estimate at
    the observed extreme on their side.  Returns {"p50": v, ...} or None
    for an empty histogram.
    """
    count = hist_json.get("count", 0)
    if not count:
        return None
    vmin, vmax = hist_json["min"], hist_json["max"]

    def bucket_key(item: Tuple[str, int]) -> int:
        k = item[0]
        if k == "<=-32":
            return -(10**6)
        if k == ">=31":
            return 10**6
        return int(k)

    buckets = sorted(hist_json["buckets"].items(), key=bucket_key)
    out: Dict[str, float] = {}
    for q in qs:
        target = q * count
        cum = 0
        est = vmax
        for k, c in buckets:
            cum += c
            if cum >= target:
                if k == "<=-32":
                    est = vmin
                elif k == ">=31":
                    est = vmax
                else:
                    est = 2.0 ** (int(k) - 0.5)  # geometric bucket midpoint
                break
        # %g keeps the label faithful to the requested quantile: 0.5 ->
        # "p50", 0.29 -> "p29" (int() would float-truncate to "p28"),
        # 0.999 -> "p99.9" (distinct from "p99", no silent collision).
        out["p%g" % (q * 100)] = min(max(est, vmin), vmax)
    return out


class Registry:
    """Thread-safe metrics store.  One process-wide instance lives in this
    module; tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}

    def counter(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.observe(value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_json() for k, h in self._hists.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class _Tracer:
    """Chrome trace-event JSONL writer (buffered, lock-guarded)."""

    _FLUSH_EVERY = 512

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._file: Optional[Any] = open(path, "w")
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "tid": 0,
                "args": {"name": "peritext-tpu"},
            }
        )

    def _emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, separators=(",", ":"))
        with self._lock:
            if self._file is None:
                return
            self._buf.append(line)
            if len(self._buf) >= self._FLUSH_EVERY:
                self._flush_locked()

    def emit_complete(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        tid: int,
        args: Optional[Dict[str, Any]],
    ) -> None:
        event: Dict[str, Any] = {
            "name": name,
            "cat": "peritext",
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            event["args"] = args
        self._emit(event)

    def emit_flow(
        self,
        name: str,
        phase: str,
        flow_id: int,
        ts_us: float,
        tid: int,
        args: Optional[Dict[str, Any]],
    ) -> None:
        """One Chrome flow event (ph s/t/f).  Binding rule: the event
        attaches to the slice covering (pid, tid, ts) — callers emit from
        inside an open span, whose complete event (written later, at span
        exit) covers this timestamp."""
        event: Dict[str, Any] = {
            "name": name,
            "cat": "peritext.flow",
            "ph": phase,
            "id": flow_id,
            "ts": ts_us,
            "pid": os.getpid(),
            "tid": tid,
        }
        if phase == "f":
            # Bind the finish to the ENCLOSING slice (the default binds to
            # the next slice that begins, which here would be arbitrary).
            event["bp"] = "e"
        if args:
            event["args"] = args
        self._emit(event)

    def _flush_locked(self) -> None:
        if self._buf and self._file is not None:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


class _NullSpan:
    """The disabled-path span: a shared, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.args = args
        self._t0 = 0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter_ns()
        # The span may outlive a disable() (e.g. a test tearing down while a
        # timer-thread flush is mid-span); record into whatever plane is
        # current — the registry/tracer never become invalid, only unused.
        _registry.observe("span." + self.name + ".seconds", (t1 - self._t0) / 1e9)
        tracer = _tracer
        if tracer is not None:
            tracer.emit_complete(
                self.name,
                self._t0 / 1e3,
                (t1 - self._t0) / 1e3,
                threading.get_ident(),
                self.args,
            )
        return False


# -- causal flow contexts -----------------------------------------------------

# Monotonic flow ids: allocation order IS causal mint order, deterministic
# given call order (no Date.now()-style wall entropy), and distinct across
# every plane in the process.
_flow_ids = itertools.count(1)
_flow_lock = threading.Lock()
_tls = threading.local()


class TraceContext:
    """One change-batch's causal identity, threaded across seams.

    ``id`` is the Chrome flow-event id; ``kind`` names the lane (the flow
    events' shared name); ``t0_ns`` is the mint time (perf_counter), so the
    terminal seam can feed the e2e latency histograms.  The phase machine
    (unstarted -> started -> finished) makes emission idempotent-safe: the
    first :func:`flow_point` emits ``s``, later ones ``t``, the terminal
    one ``f``, and anything after a finish is ignored — a retried flush
    cannot corrupt the triplet.
    """

    __slots__ = ("id", "kind", "t0_ns", "meta", "_phase")

    def __init__(self, kind: str, meta: Optional[Dict[str, Any]] = None) -> None:
        self.id = next(_flow_ids)
        self.kind = kind
        self.t0_ns = time.perf_counter_ns()
        self.meta = meta
        self._phase = 0  # 0 unstarted, 1 started, 2 finished


class _Flowing:
    """Scoped thread-local flow propagation (replace semantics: the inner
    scope's lanes are what downstream seams join)."""

    __slots__ = ("ctxs", "prev")

    def __init__(self, ctxs: Tuple["TraceContext", ...]) -> None:
        self.ctxs = ctxs
        self.prev: Tuple["TraceContext", ...] = ()

    def __enter__(self) -> "_Flowing":
        self.prev = getattr(_tls, "flows", ())
        _tls.flows = self.ctxs
        return self

    def __exit__(self, *exc: Any) -> bool:
        _tls.flows = self.prev
        return False


# -- flight recorder ----------------------------------------------------------


class _FlightRecorder:
    """Fixed-capacity ring of recent structured events.

    Preallocated slots, one lock, O(1) per record; overwrites count as
    ``dropped`` so post-mortems know how much history the ring held vs
    lost.  Never grows — the always-on cost is bounded by construction.
    """

    __slots__ = ("cap", "buf", "n", "dropped", "lock")

    def __init__(self, cap: int) -> None:
        self.cap = max(1, cap)
        self.buf: List[Any] = [None] * self.cap
        self.n = 0
        self.dropped = 0
        self.lock = threading.Lock()

    def record(
        self,
        t_us: float,
        site: str,
        flow_id: Optional[int],
        outcome: str,
        fields: Optional[Dict[str, Any]],
    ) -> None:
        with self.lock:
            if self.n >= self.cap:
                self.dropped += 1
            self.buf[self.n % self.cap] = (t_us, site, flow_id, outcome, fields)
            self.n += 1

    def events(self) -> List[Dict[str, Any]]:
        with self.lock:
            if self.n <= self.cap:
                items = list(self.buf[: self.n])
            else:
                i = self.n % self.cap
                items = list(self.buf[i:]) + list(self.buf[:i])
        out = []
        for t_us, site, flow_id, outcome, fields in items:
            event: Dict[str, Any] = {"ts_us": t_us, "site": site, "outcome": outcome}
            if flow_id is not None:
                event["flow"] = flow_id
            if fields:
                event["fields"] = fields
            out.append(event)
        return out


class _MetricsFlusher(threading.Thread):
    """Periodic metrics-snapshot flush (PERITEXT_METRICS_INTERVAL): the
    atexit dump dies exactly when it matters most (SIGKILLed bench child,
    wedged-relay timeout); this daemon leaves a recent atomic snapshot
    behind instead."""

    def __init__(self, interval: float) -> None:
        super().__init__(daemon=True, name="peritext-metrics-flusher")
        self.interval = interval
        self.stop_event = threading.Event()

    def run(self) -> None:
        while not self.stop_event.wait(self.interval):
            try:
                dump_metrics()
            except Exception:  # a full disk must not kill the flusher
                import logging

                logging.getLogger(__name__).warning(
                    "periodic metrics flush failed", exc_info=True
                )


# -- the process-wide plane ---------------------------------------------------

_registry = Registry()
_tracer: Optional[_Tracer] = None
_metrics_path: Optional[str] = None
_config_lock = threading.Lock()
_atexit_registered = False
_recorder: Optional[_FlightRecorder] = None
_blackbox_dir: Optional[str] = None
_blackbox_seq = itertools.count(1)
_MAX_BLACKBOX_DUMPS = 32
_flusher: Optional[_MetricsFlusher] = None


def counter(name: str, n: int = 1) -> None:
    """Add ``n`` to a monotonic counter (no-op while disabled)."""
    if enabled:
        _registry.counter(name, n)


def gauge(name: str, value: float) -> None:
    """Set a last-value gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge(name, value)


def gauge_max(name: str, value: float) -> None:
    """Raise a high-water-mark gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge_max(name, value)


def observe(name: str, value: float) -> None:
    """Record a value into a log2-bucket histogram (no-op while disabled)."""
    if enabled:
        _registry.observe(name, value)


def span(name: str, **args: Any) -> Any:
    """Context manager timing a region.  Disabled: returns a shared no-op
    singleton (zero allocation).  Enabled: records a ``span.<name>.seconds``
    histogram entry and, when tracing, a Chrome complete event."""
    if not enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def flow(kind: str, **meta: Any) -> Optional[TraceContext]:
    """Mint a causal trace context (None while disabled — call sites keep
    the one-attr-check contract by guarding on ``telemetry.enabled``).
    ``meta`` rides on the flow's start event (change ids, actor, count)."""
    if not enabled:
        return None
    return TraceContext(kind, meta or None)


def flow_point(
    ctx: Optional[TraceContext], terminal: bool = False, **args: Any
) -> None:
    """Mark the current seam on a flow's lane (no-op for None / no tracer).

    MUST be called from inside an open :func:`span` — flow events bind to
    the slice covering their timestamp on this thread.  The first point
    emits the flow start (``s``), later ones steps (``t``), and
    ``terminal=True`` the finish (``f``); points after a finish are
    dropped, so retried seams cannot emit a second finish."""
    if ctx is None:
        return
    tracer = _tracer
    if tracer is None:
        return
    now_us = time.perf_counter_ns() / 1e3
    with _flow_lock:
        phase0 = ctx._phase
        if phase0 == 2:
            return
        start = phase0 == 0
        ctx._phase = 2 if terminal else 1
    tid = threading.get_ident()
    if start:
        tracer.emit_flow(ctx.kind, "s", ctx.id, now_us, tid, ctx.meta)
    if terminal:
        tracer.emit_flow(ctx.kind, "f", ctx.id, now_us, tid, args or None)
    elif not start:
        tracer.emit_flow(ctx.kind, "t", ctx.id, now_us, tid, args or None)


def flow_steps(terminal: bool = False, **args: Any) -> None:
    """flow_point for every lane propagated onto this thread (deep seams —
    ingest attempts, degradation, readback — join whatever lanes the
    enclosing flush/change/delivery scoped in via :func:`flowing`)."""
    for ctx in getattr(_tls, "flows", ()):
        flow_point(ctx, terminal=terminal, **args)


def flowing(ctxs: Sequence[Optional[TraceContext]]) -> Any:
    """Scope flow contexts onto this thread for downstream seams.  Returns
    an allocation-free no-op for an empty/None-only sequence, so disabled
    call sites pay nothing."""
    live = tuple(c for c in ctxs if c is not None)
    if not live:
        return _NULL_SPAN
    return _Flowing(live)


def current_flows() -> Tuple[TraceContext, ...]:
    """The lanes scoped onto this thread (empty tuple when none)."""
    return getattr(_tls, "flows", ())


def current_flow() -> Optional[TraceContext]:
    """The first lane scoped onto this thread, or None — the one to stamp
    on single-flow recorder events."""
    flows = getattr(_tls, "flows", ())
    return flows[0] if flows else None


def flow_elapsed_s(ctx: TraceContext) -> float:
    """Seconds since the context was minted (feeds the e2e histograms)."""
    return (time.perf_counter_ns() - ctx.t0_ns) / 1e9


def record(
    site: str,
    flow: Optional[TraceContext] = None,
    outcome: str = "ok",
    **fields: Any,
) -> None:
    """Append one structured event to the flight-recorder ring (no-op
    while disabled).  Launch-level granularity, like every other site."""
    if not enabled:
        return
    rec = _recorder
    if rec is None:
        rec = _ensure_recorder()
    rec.record(
        time.perf_counter_ns() / 1e3,
        site,
        None if flow is None else flow.id,
        outcome,
        fields or None,
    )


def _ensure_recorder() -> _FlightRecorder:
    global _recorder
    with _config_lock:
        if _recorder is None:
            try:
                cap = int(os.environ.get("PERITEXT_BLACKBOX_RING", "512") or 512)
            except ValueError:
                cap = 512
            _recorder = _FlightRecorder(cap)
        return _recorder


def recorder_events() -> List[Dict[str, Any]]:
    """The ring's events, oldest first (empty when nothing recorded)."""
    rec = _recorder
    return [] if rec is None else rec.events()


def recorder_stats() -> Tuple[int, int]:
    """(events recorded, events dropped by ring overwrite)."""
    rec = _recorder
    return (0, 0) if rec is None else (rec.n, rec.dropped)


def blackbox_dir() -> Optional[str]:
    """The armed black-box dump directory, or None."""
    return _blackbox_dir


def blackbox_dump(reason: str, **info: Any) -> Optional[str]:
    """Write a post-mortem dump (ring + registry snapshot + summary) to the
    ``PERITEXT_BLACKBOX`` directory; returns the path or None when unarmed.

    Atomic (tmp+rename), monotonic per-process sequence numbers, and capped
    at a few dozen dumps per process so a wedge storm cannot fill the disk
    (skips count as ``blackbox.skipped``).  Never raises — a full disk must
    not turn a post-mortem into a second failure."""
    d = _blackbox_dir
    if d is None:
        return None
    seq = next(_blackbox_seq)
    if seq > _MAX_BLACKBOX_DUMPS:
        if enabled:
            _registry.counter("blackbox.skipped")
        return None
    rec = _recorder
    payload = {
        "reason": reason,
        "info": info,
        "pid": os.getpid(),
        "ring": [] if rec is None else rec.events(),
        "ring_dropped": 0 if rec is None else rec.dropped,
        "metrics": snapshot(),
        "summary": summary(),
    }
    path = os.path.join(d, f"blackbox-{os.getpid()}-{seq:04d}-{reason}.json")
    tmp = path + ".tmp"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        import logging

        logging.getLogger(__name__).warning(
            "black-box dump to %r failed", path, exc_info=True
        )
        return None
    if enabled:
        _registry.counter("blackbox.dumps")
    flush_trace()  # the trace should cover everything the dump names
    return path


def snapshot() -> Dict[str, Any]:
    """Full registry contents: {"counters", "gauges", "histograms"}."""
    return _registry.snapshot()


def summary() -> Dict[str, Any]:
    """Compact well-known subset for bench lines and chaos-run footers:
    launch/retry/degradation tallies, merge-path choices, queue depth,
    traffic bytes, and the mirrored fault counters.  Only keys that saw
    traffic appear, so the summary stays one short JSON object."""
    snap = _registry.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    out: Dict[str, Any] = {}
    for key, src in (
        ("launches", "ingest.launches"),
        ("launch_attempts", "ingest.launch_attempts"),
        ("launch_retries", "ingest.launch_retries"),
        ("launch_failures", "ingest.launch_failures"),
        ("degraded_batches", "ingest.degraded_batches"),
        ("h2d_bytes", "ingest.h2d_bytes"),
        ("d2h_bytes", "ingest.d2h_bytes"),
        ("queue_flushes", "queue.flushes"),
        ("queue_reenqueues", "queue.reenqueues"),
        ("queue_shed", "queue.shed"),
        ("queue_coalesced", "queue.coalesced"),
        ("queue_blocked", "queue.blocked"),
        ("sync_deferred", "sync.deferred"),
        ("health_fastfails", "health.fastfail"),
        ("pubsub_delivered", "pubsub.delivered"),
        ("stream_cohorts", "stream.cohorts"),
        ("checkpoint_corrupt_fallbacks", "checkpoint.corrupt_fallbacks"),
        ("local_gen_rollbacks", "doc.local_gen_rollbacks"),
        ("blackbox_dumps", "blackbox.dumps"),
        ("blackbox_skipped", "blackbox.skipped"),
        ("window_fallbacks", "ingest.window_fallbacks"),
        ("window_rebuilds", "ingest.window_rebuilds"),
    ):
        if src in counters:
            out[key] = counters[src]
    paths = {
        name.rsplit(".", 1)[1]: n
        for name, n in counters.items()
        if name.startswith("ingest.path.")
    }
    if paths:
        out["merge_path"] = paths
    if "queue.depth_max" in gauges:
        out["queue_depth_max"] = gauges["queue.depth_max"]
    if "stream.inflight_max" in gauges:
        out["stream_inflight_max"] = gauges["stream.inflight_max"]
    faults_mirror = {
        name[len("faults.") :]: n
        for name, n in counters.items()
        if name.startswith("faults.")
    }
    if faults_mirror:
        out["faults"] = faults_mirror
    health_mirror = {
        name[len("health.") :]: n
        for name, n in counters.items()
        if name.startswith("health.") and name != "health.fastfail"
    }
    if health_mirror:
        out["health"] = health_mirror
    # Serving-plane tallies (runtime/serve.py): present whenever serve
    # traffic happened, so bench JSON stamps and the fuzz --chaos footer
    # carry admission/batching/shed behavior without a separate plumbing
    # path.  The e2e.admit_to_applied percentiles ride in out["e2e"].
    serve_mirror = {
        name[len("serve.") :]: n
        for name, n in counters.items()
        if name.startswith("serve.")
    }
    if serve_mirror:
        if "serve.depth_max" in gauges:
            serve_mirror["depth_max"] = gauges["serve.depth_max"]
        out["serve"] = serve_mirror
    # End-to-end latency percentiles (the causal-flow plane's terminal
    # seams) + the key per-seam latencies, estimated from the log2
    # histograms — the "why was p99 40x the median" numbers a one-line
    # bench stamp or chaos footer can carry.
    hists = snap["histograms"]
    e2e = {}
    for name, h in hists.items():
        if name.startswith("e2e."):
            q = estimate_quantiles(h)
            if q is not None:
                q["count"] = h["count"]
                e2e[name[len("e2e.") :]] = q
    if e2e:
        out["e2e"] = e2e
    lat = {}
    for label, src in (
        ("ingest_launch_s", "span.ingest.launch_attempt.seconds"),
        ("queue_flush_s", "queue.flush_seconds"),
    ):
        if src in hists:
            q = estimate_quantiles(hists[src])
            if q is not None:
                lat[label] = q
    if lat:
        out["latency"] = lat
    rec_n, rec_dropped = recorder_stats()
    if rec_n:
        out["recorder_events"] = rec_n
        out["recorder_dropped"] = rec_dropped
    return out


def trace_path() -> Optional[str]:
    """Path of the active trace file, or None when not tracing."""
    tracer = _tracer
    return None if tracer is None else tracer.path


def enable(
    trace: Optional[str] = None,
    metrics: Optional[str] = None,
    blackbox: Optional[str] = None,
    metrics_interval: Optional[float] = None,
) -> None:
    """Turn collection on.  ``trace`` opens (truncating) a Chrome trace
    JSONL file; ``metrics`` schedules a snapshot dump at interpreter exit
    (``metrics_interval`` > 0 additionally flushes it periodically from a
    daemon thread); ``blackbox`` arms failure dumps to a directory.  All
    may be omitted — a bare ``enable()`` collects registry metrics only."""
    global enabled, _tracer, _metrics_path, _blackbox_dir, _flusher
    with _config_lock:
        if trace:
            if _tracer is not None and _tracer.path != trace:
                _tracer.close()
                _tracer = None
            if _tracer is None:
                _tracer = _Tracer(trace)
        if metrics:
            _metrics_path = metrics
        if blackbox:
            _blackbox_dir = blackbox
        _ensure_atexit_locked()
        enabled = True
        if metrics_interval and metrics_interval > 0 and _metrics_path:
            if _flusher is not None and _flusher.interval != metrics_interval:
                _flusher.stop_event.set()
                _flusher = None
            if _flusher is None:
                _flusher = _MetricsFlusher(metrics_interval)
                _flusher.start()


def disable() -> None:
    """Stop collection (registry contents and the trace file are kept —
    re-enable resumes into them; use :func:`reset` for a pristine plane)."""
    global enabled
    enabled = False


def reset() -> None:
    """Back to a pristine, disabled plane: counters cleared, tracer closed,
    exit dump canceled, recorder ring dropped, black-box disarmed, the
    periodic flusher stopped.  Does NOT re-read the environment (tests own
    the lifecycle after a reset)."""
    global enabled, _tracer, _metrics_path, _recorder, _blackbox_dir, _flusher
    with _config_lock:
        enabled = False
        if _tracer is not None:
            _tracer.close()
            _tracer = None
        _metrics_path = None
        _recorder = None
        _blackbox_dir = None
        if _flusher is not None:
            _flusher.stop_event.set()
            _flusher = None
        _registry.clear()


def flush_trace() -> None:
    """Force buffered trace events to disk (the tracer also flushes every
    few hundred events and at exit)."""
    tracer = _tracer
    if tracer is not None:
        tracer.flush()


_dump_lock = threading.Lock()


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Write the metrics snapshot (+ summary) as JSON, atomically.
    Defaults to the ``PERITEXT_METRICS`` path; returns the path written or
    None.  Serialized under a lock AND written via a per-writer tmp name:
    the periodic flusher can race the atexit dump (or a programmatic
    call), and two writers sharing one tmp path would rename an
    interleaved file into place — exactly the corrupt snapshot this
    feature exists to prevent."""
    path = path or _metrics_path
    if not path:
        return None
    payload = snapshot()
    payload["summary"] = summary()
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with _dump_lock:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    return path


def _at_exit() -> None:
    try:
        if _metrics_path:
            dump_metrics(_metrics_path)
    finally:
        tracer = _tracer
        if tracer is not None:
            tracer.flush()


def _ensure_atexit_locked() -> None:
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(_at_exit)
        _atexit_registered = True


def _activate_from_env() -> None:
    """Import-time activation from PERITEXT_TRACE / PERITEXT_METRICS /
    PERITEXT_BLACKBOX (+ PERITEXT_METRICS_INTERVAL).

    A bad trace path (missing directory, permissions) must not take the
    whole product down at import — observability degrades to untraced
    collection with a warning instead.  Programmatic :func:`enable` still
    raises, so deliberate callers see the real error."""
    trace = os.environ.get("PERITEXT_TRACE")
    metrics = os.environ.get("PERITEXT_METRICS")
    blackbox = os.environ.get("PERITEXT_BLACKBOX")
    try:
        interval = float(os.environ.get("PERITEXT_METRICS_INTERVAL", "0") or 0)
    except ValueError:
        interval = 0.0
    if not (trace or metrics or blackbox):
        return
    try:
        enable(
            trace=trace or None,
            metrics=metrics or None,
            blackbox=blackbox or None,
            metrics_interval=interval or None,
        )
    except OSError as exc:
        import logging

        logging.getLogger(__name__).warning(
            "PERITEXT_TRACE=%r unusable (%s); continuing without a tracer",
            trace,
            exc,
        )
        enable(
            metrics=metrics or None,
            blackbox=blackbox or None,
            metrics_interval=interval or None,
        )


_activate_from_env()
