"""Process-wide telemetry plane: metrics registry + span tracer.

Until this module existed the only windows into runtime behavior were the
ad-hoc ``TpuUniverse.stats`` dict, ``FaultPlan.stats`` on the chaos plane,
and the one-line bench JSON — questions like "how many launches retried",
"which merge path actually ran", or "did the cohort pipeline overlap" took
printf archaeology.  This module is the shared substrate every layer
reports through:

- a **metrics registry**: monotonic counters, last/max gauges, and
  histograms with fixed log2 buckets (64 buckets; bucket *i* counts values
  in ``[2**(i-33), 2**(i-32))``, exact over ``[2**-32, 2**30)`` — sub-ns
  to ~34-year durations — with explicit ``"<=-32"`` / ``">=31"`` overflow
  buckets at the ends; larger magnitudes belong in counters);
- a **span tracer**: ``with telemetry.span("ingest.launch"): ...`` records
  a Chrome trace-event-format *complete event* (``"ph": "X"``) with
  monotonic microsecond timestamps and the recording thread's id, so
  nested spans render as flame stacks per thread in Perfetto /
  chrome://tracing.  Every span also lands in the registry as a
  ``span.<name>.seconds`` histogram.

Activation
==========

``PERITEXT_TRACE=<path>`` writes trace events as JSONL (one JSON object
per line; wrap with ``jq -s . trace.jsonl > trace.json`` for
chrome://tracing — Perfetto's importer reads the newline-delimited form
directly).  ``PERITEXT_METRICS=<path>`` dumps a JSON metrics snapshot at
interpreter exit.  Either env var enables collection at import; tests and
embedders call :func:`enable` / :func:`disable` / :func:`reset`
programmatically.

The overhead contract
=====================

Instrumented call sites sit inside the ingest hot loop, so the DISABLED
path must be near-free: every site guards on the single module attribute
:data:`enabled` —

    if telemetry.enabled:
        telemetry.counter("ingest.launch_retries")

— one attribute check, no call, no allocation, no lock taken.  (The
module-level helpers also re-check ``enabled`` internally, so unguarded
sites are merely slower, never wrong.)  ``span()`` when disabled returns a
shared no-op singleton, so even unguarded ``with telemetry.span(...)``
allocates nothing.  tests/test_telemetry.py pins both properties.

Enabled, the cost is one small dict update under a lock per event —
instrumentation is launch-level (per kernel launch / flush / cohort),
never per-op, so a telemetry-on run stays within a couple percent of
telemetry-off on the patched-fleet steady state.

Thread safety: all registry mutation happens under one lock (concurrent
``ChangeQueue`` timer flushes and foreground ingest cannot lose
increments), and each ``span()`` call returns a fresh span object, so
nested or cross-thread spans cannot corrupt one another.
"""
from __future__ import annotations

import atexit
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

# NOTE: `enabled` is deliberately NOT in __all__ — `from telemetry import
# enabled` would snapshot the flag at import time and make guards
# permanently dead.  The one correct spelling is the attribute form the
# docstring prescribes: `telemetry.enabled`.
__all__ = [
    "enable",
    "disable",
    "reset",
    "counter",
    "gauge",
    "gauge_max",
    "observe",
    "span",
    "snapshot",
    "summary",
    "dump_metrics",
    "flush_trace",
    "trace_path",
]

# THE hot-path gate (see the overhead contract above).
enabled = False

_N_BUCKETS = 64
_BUCKET_OFFSET = 32  # bucket i counts values v with frexp(v)[1] == i - 32


class _Histogram:
    """Fixed-log2-bucket histogram (+ count/sum/min/max)."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets = [0] * _N_BUCKETS

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if value > 0:
            i = min(_N_BUCKETS - 1, max(0, math.frexp(value)[1] + _BUCKET_OFFSET))
        else:
            i = 0  # non-positive values share the smallest bucket
        self.buckets[i] += 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            # Keyed by the bucket's upper-bound exponent: a value v landed
            # in bucket "e" iff 2**(e-1) <= v < 2**e.  The clamped end
            # buckets say so explicitly — "<=-32" holds everything below
            # 2**-32 (including non-positive values), ">=31" everything
            # from 2**30 up — so a snapshot can never silently claim an
            # out-of-range value sat inside a nominal bucket.
            "buckets": {
                (
                    "<=-32"
                    if i == 0
                    else ">=31"
                    if i == _N_BUCKETS - 1
                    else str(i - _BUCKET_OFFSET)
                ): c
                for i, c in enumerate(self.buckets)
                if c
            },
        }


class Registry:
    """Thread-safe metrics store.  One process-wide instance lives in this
    module; tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}

    def counter(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.observe(value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_json() for k, h in self._hists.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class _Tracer:
    """Chrome trace-event JSONL writer (buffered, lock-guarded)."""

    _FLUSH_EVERY = 512

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._file: Optional[Any] = open(path, "w")
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "tid": 0,
                "args": {"name": "peritext-tpu"},
            }
        )

    def _emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, separators=(",", ":"))
        with self._lock:
            if self._file is None:
                return
            self._buf.append(line)
            if len(self._buf) >= self._FLUSH_EVERY:
                self._flush_locked()

    def emit_complete(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        tid: int,
        args: Optional[Dict[str, Any]],
    ) -> None:
        event: Dict[str, Any] = {
            "name": name,
            "cat": "peritext",
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            event["args"] = args
        self._emit(event)

    def _flush_locked(self) -> None:
        if self._buf and self._file is not None:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


class _NullSpan:
    """The disabled-path span: a shared, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.args = args
        self._t0 = 0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter_ns()
        # The span may outlive a disable() (e.g. a test tearing down while a
        # timer-thread flush is mid-span); record into whatever plane is
        # current — the registry/tracer never become invalid, only unused.
        _registry.observe("span." + self.name + ".seconds", (t1 - self._t0) / 1e9)
        tracer = _tracer
        if tracer is not None:
            tracer.emit_complete(
                self.name,
                self._t0 / 1e3,
                (t1 - self._t0) / 1e3,
                threading.get_ident(),
                self.args,
            )
        return False


# -- the process-wide plane ---------------------------------------------------

_registry = Registry()
_tracer: Optional[_Tracer] = None
_metrics_path: Optional[str] = None
_config_lock = threading.Lock()
_atexit_registered = False


def counter(name: str, n: int = 1) -> None:
    """Add ``n`` to a monotonic counter (no-op while disabled)."""
    if enabled:
        _registry.counter(name, n)


def gauge(name: str, value: float) -> None:
    """Set a last-value gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge(name, value)


def gauge_max(name: str, value: float) -> None:
    """Raise a high-water-mark gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge_max(name, value)


def observe(name: str, value: float) -> None:
    """Record a value into a log2-bucket histogram (no-op while disabled)."""
    if enabled:
        _registry.observe(name, value)


def span(name: str, **args: Any) -> Any:
    """Context manager timing a region.  Disabled: returns a shared no-op
    singleton (zero allocation).  Enabled: records a ``span.<name>.seconds``
    histogram entry and, when tracing, a Chrome complete event."""
    if not enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def snapshot() -> Dict[str, Any]:
    """Full registry contents: {"counters", "gauges", "histograms"}."""
    return _registry.snapshot()


def summary() -> Dict[str, Any]:
    """Compact well-known subset for bench lines and chaos-run footers:
    launch/retry/degradation tallies, merge-path choices, queue depth,
    traffic bytes, and the mirrored fault counters.  Only keys that saw
    traffic appear, so the summary stays one short JSON object."""
    snap = _registry.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    out: Dict[str, Any] = {}
    for key, src in (
        ("launches", "ingest.launches"),
        ("launch_attempts", "ingest.launch_attempts"),
        ("launch_retries", "ingest.launch_retries"),
        ("launch_failures", "ingest.launch_failures"),
        ("degraded_batches", "ingest.degraded_batches"),
        ("h2d_bytes", "ingest.h2d_bytes"),
        ("d2h_bytes", "ingest.d2h_bytes"),
        ("queue_flushes", "queue.flushes"),
        ("queue_reenqueues", "queue.reenqueues"),
        ("queue_shed", "queue.shed"),
        ("queue_coalesced", "queue.coalesced"),
        ("queue_blocked", "queue.blocked"),
        ("sync_deferred", "sync.deferred"),
        ("health_fastfails", "health.fastfail"),
        ("pubsub_delivered", "pubsub.delivered"),
        ("stream_cohorts", "stream.cohorts"),
        ("checkpoint_corrupt_fallbacks", "checkpoint.corrupt_fallbacks"),
        ("local_gen_rollbacks", "doc.local_gen_rollbacks"),
    ):
        if src in counters:
            out[key] = counters[src]
    paths = {
        name.rsplit(".", 1)[1]: n
        for name, n in counters.items()
        if name.startswith("ingest.path.")
    }
    if paths:
        out["merge_path"] = paths
    if "queue.depth_max" in gauges:
        out["queue_depth_max"] = gauges["queue.depth_max"]
    if "stream.inflight_max" in gauges:
        out["stream_inflight_max"] = gauges["stream.inflight_max"]
    faults_mirror = {
        name[len("faults.") :]: n
        for name, n in counters.items()
        if name.startswith("faults.")
    }
    if faults_mirror:
        out["faults"] = faults_mirror
    health_mirror = {
        name[len("health.") :]: n
        for name, n in counters.items()
        if name.startswith("health.") and name != "health.fastfail"
    }
    if health_mirror:
        out["health"] = health_mirror
    return out


def trace_path() -> Optional[str]:
    """Path of the active trace file, or None when not tracing."""
    tracer = _tracer
    return None if tracer is None else tracer.path


def enable(trace: Optional[str] = None, metrics: Optional[str] = None) -> None:
    """Turn collection on.  ``trace`` opens (truncating) a Chrome trace
    JSONL file; ``metrics`` schedules a snapshot dump at interpreter exit.
    Either may be omitted — a bare ``enable()`` collects registry metrics
    only."""
    global enabled, _tracer, _metrics_path
    with _config_lock:
        if trace:
            if _tracer is not None and _tracer.path != trace:
                _tracer.close()
                _tracer = None
            if _tracer is None:
                _tracer = _Tracer(trace)
        if metrics:
            _metrics_path = metrics
        _ensure_atexit_locked()
        enabled = True


def disable() -> None:
    """Stop collection (registry contents and the trace file are kept —
    re-enable resumes into them; use :func:`reset` for a pristine plane)."""
    global enabled
    enabled = False


def reset() -> None:
    """Back to a pristine, disabled plane: counters cleared, tracer closed,
    exit dump canceled.  Does NOT re-read the environment (tests own the
    lifecycle after a reset)."""
    global enabled, _tracer, _metrics_path
    with _config_lock:
        enabled = False
        if _tracer is not None:
            _tracer.close()
            _tracer = None
        _metrics_path = None
        _registry.clear()


def flush_trace() -> None:
    """Force buffered trace events to disk (the tracer also flushes every
    few hundred events and at exit)."""
    tracer = _tracer
    if tracer is not None:
        tracer.flush()


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Write the metrics snapshot (+ summary) as JSON.  Defaults to the
    ``PERITEXT_METRICS`` path; returns the path written or None."""
    path = path or _metrics_path
    if not path:
        return None
    payload = snapshot()
    payload["summary"] = summary()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def _at_exit() -> None:
    try:
        if _metrics_path:
            dump_metrics(_metrics_path)
    finally:
        tracer = _tracer
        if tracer is not None:
            tracer.flush()


def _ensure_atexit_locked() -> None:
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(_at_exit)
        _atexit_registered = True


def _activate_from_env() -> None:
    """Import-time activation from PERITEXT_TRACE / PERITEXT_METRICS.

    A bad trace path (missing directory, permissions) must not take the
    whole product down at import — observability degrades to untraced
    collection with a warning instead.  Programmatic :func:`enable` still
    raises, so deliberate callers see the real error."""
    trace = os.environ.get("PERITEXT_TRACE")
    metrics = os.environ.get("PERITEXT_METRICS")
    if not (trace or metrics):
        return
    try:
        enable(trace=trace or None, metrics=metrics or None)
    except OSError as exc:
        import logging

        logging.getLogger(__name__).warning(
            "PERITEXT_TRACE=%r unusable (%s); continuing without a tracer",
            trace,
            exc,
        )
        enable(metrics=metrics or None)


_activate_from_env()
