"""Process-wide telemetry plane: metrics registry + span tracer.

Until this module existed the only windows into runtime behavior were the
ad-hoc ``TpuUniverse.stats`` dict, ``FaultPlan.stats`` on the chaos plane,
and the one-line bench JSON — questions like "how many launches retried",
"which merge path actually ran", or "did the cohort pipeline overlap" took
printf archaeology.  This module is the shared substrate every layer
reports through:

- a **metrics registry**: monotonic counters, last/max gauges, and
  histograms with fixed log2 buckets (64 buckets; bucket *i* counts values
  in ``[2**(i-33), 2**(i-32))``, exact over ``[2**-32, 2**30)`` — sub-ns
  to ~34-year durations — with explicit ``"<=-32"`` / ``">=31"`` overflow
  buckets at the ends; larger magnitudes belong in counters);
- a **span tracer**: ``with telemetry.span("ingest.launch"): ...`` records
  a Chrome trace-event-format *complete event* (``"ph": "X"``) with
  monotonic microsecond timestamps and the recording thread's id, so
  nested spans render as flame stacks per thread in Perfetto /
  chrome://tracing.  Every span also lands in the registry as a
  ``span.<name>.seconds`` histogram;
- a **causal flow plane**: a :class:`TraceContext` (monotonic id — no
  wall-clock entropy) is minted where a change enters the system
  (``ChangeQueue.enqueue``, ``TpuDoc.change``, ``Publisher.publish``,
  cohort launch) and threaded through every seam it crosses, emitting
  Chrome *flow events* (``ph: s/t/f``) bound to the enclosing span, so
  Perfetto draws one arrow-connected lane per change across threads.
  The terminal seam feeds end-to-end latency histograms
  (``e2e.enqueue_to_applied``, ``e2e.publish_to_delivered``, ...).
  Propagation is thread-local (:func:`flowing` / :func:`current_flows`)
  so deep seams (ingest retries, degradation, readback) join the lane
  without threading a context argument through every signature;
- a **flight recorder**: a fixed-capacity ring of recent structured
  events (site, flow id, outcome, µs) that is always recording while
  telemetry is enabled.  On a failure worth a post-mortem (launch-budget
  exhaustion, breaker trip, checkpoint corruption, unhandled ingest
  exception, SLO breach) :func:`blackbox_dump` writes the ring + a
  registry snapshot to ``PERITEXT_BLACKBOX=<dir>`` — the post-mortem for
  the wedged-relay failure mode where the atexit-only dump dies with the
  process.  Dumps are **rate-limited per reason**
  (``PERITEXT_BLACKBOX_COOLDOWN``, default 30s): a breach or trip storm
  writes one dump per reason per cooldown instead of eating the 32-dump
  cap before the interesting dump lands (skips count as
  ``blackbox.deduped``);
- **tail-sampled flow tracing** (``PERITEXT_TRACE_SAMPLE=<p>`` +
  ``PERITEXT_TRACE_TAIL=slow:<ms>|error|breach``): with head sampling
  below 1.0 the flow plane buffers each lane's events instead of writing
  them, and decides at the terminal seam — a lane is kept when its flow
  id head-samples in, OR (tail rules) when it was slow, touched an
  error/retry/degrade seam, or terminated while an SLO breach was active.
  Interesting lanes therefore survive at 100% even at ``SAMPLE=0``, which
  is what makes an always-on production tracer affordable
  (``trace.lanes_kept`` / ``trace.lanes_dropped`` count the verdicts;
  span/complete events are never sampled, so kept lanes still bind);
- an **SLO feed**: :mod:`peritext_tpu.runtime.slo` registers sink maps via
  :func:`_install_slo_sinks`; :func:`counter` / :func:`observe` (and span
  exits) forward matching names to the active plan's sliding-window
  evaluators.  With no plan installed the cost is one module-attribute
  load + ``None`` check per already-enabled call;
- a **live status surface**: :func:`status` assembles one operator-facing
  JSON object — breaker states, queue depth/high-water, per-session serve
  lane depth + deficit, per-shard occupancy + fleet compiled-shape
  pressure (via :func:`register_status_source`), windowed-merge
  engagement, per-SLO compliance/burn, and sampler verdicts.
  ``PERITEXT_STATUS=<path>`` writes it periodically (atomic tmp+rename,
  riding the metrics flusher thread) and at exit; ``scripts/ops_top.py``
  renders the file live in a terminal.

Activation
==========

``PERITEXT_TRACE=<path>`` writes trace events as JSONL (one JSON object
per line; wrap with ``jq -s . trace.jsonl > trace.json`` for
chrome://tracing — Perfetto's importer reads the newline-delimited form
directly).  ``PERITEXT_METRICS=<path>`` dumps a JSON metrics snapshot at
interpreter exit; ``PERITEXT_METRICS_INTERVAL=<secs>`` additionally
flushes that snapshot periodically from a daemon thread (atomic
tmp+rename), so a SIGKILLed/timed-out child leaves a recent snapshot
instead of nothing.  ``PERITEXT_BLACKBOX=<dir>`` arms the flight
recorder's failure dumps (``PERITEXT_BLACKBOX_RING`` sizes the ring,
default 512 events; ``PERITEXT_BLACKBOX_COOLDOWN`` the per-reason dump
rate limit).  ``PERITEXT_STATUS=<path>`` arms the periodic status
surface (cadence: ``PERITEXT_METRICS_INTERVAL``, defaulting to 2s when
only the status path is set).  ``PERITEXT_TRACE_SAMPLE`` /
``PERITEXT_TRACE_TAIL`` / ``PERITEXT_TRACE_SAMPLE_SEED`` configure
flow-lane sampling (:func:`set_trace_sampling`).  Any of these env vars
enables collection at import; tests and embedders call :func:`enable` /
:func:`disable` / :func:`reset` programmatically.

The overhead contract
=====================

Instrumented call sites sit inside the ingest hot loop, so the DISABLED
path must be near-free: every site guards on the single module attribute
:data:`enabled` —

    if telemetry.enabled:
        telemetry.counter("ingest.launch_retries")

— one attribute check, no call, no allocation, no lock taken.  (The
module-level helpers also re-check ``enabled`` internally, so unguarded
sites are merely slower, never wrong.)  ``span()`` when disabled returns a
shared no-op singleton, so even unguarded ``with telemetry.span(...)``
allocates nothing.  tests/test_telemetry.py pins both properties.

Enabled, the cost is one small dict update under a lock per event —
instrumentation is launch-level (per kernel launch / flush / cohort),
never per-op, so a telemetry-on run stays within a couple percent of
telemetry-off on the patched-fleet steady state.

Thread safety: all registry mutation happens under one lock (concurrent
``ChangeQueue`` timer flushes and foreground ingest cannot lose
increments), and each ``span()`` call returns a fresh span object, so
nested or cross-thread spans cannot corrupt one another.
"""
from __future__ import annotations

import atexit
import itertools
import json
import math
import os
import random
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# NOTE: `enabled` is deliberately NOT in __all__ — `from telemetry import
# enabled` would snapshot the flag at import time and make guards
# permanently dead.  The one correct spelling is the attribute form the
# docstring prescribes: `telemetry.enabled`.
__all__ = [
    "enable",
    "disable",
    "reset",
    "counter",
    "gauge",
    "gauge_max",
    "observe",
    "span",
    "snapshot",
    "summary",
    "dump_metrics",
    "flush_trace",
    "trace_path",
    "TraceContext",
    "flow",
    "flow_point",
    "flow_steps",
    "flowing",
    "current_flows",
    "current_flow",
    "flow_elapsed_s",
    "flow_keep",
    "set_trace_sampling",
    "sampling_active",
    "status",
    "dump_status",
    "register_status_source",
    "record",
    "recorder_events",
    "recorder_stats",
    "blackbox_dir",
    "blackbox_dump",
    "estimate_quantiles",
]

# THE hot-path gate (see the overhead contract above).
enabled = False

_N_BUCKETS = 64
_BUCKET_OFFSET = 32  # bucket i counts values v with frexp(v)[1] == i - 32


class _Histogram:
    """Fixed-log2-bucket histogram (+ count/sum/min/max)."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets = [0] * _N_BUCKETS

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if value > 0:
            i = min(_N_BUCKETS - 1, max(0, math.frexp(value)[1] + _BUCKET_OFFSET))
        else:
            i = 0  # non-positive values share the smallest bucket
        self.buckets[i] += 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            # Keyed by the bucket's upper-bound exponent: a value v landed
            # in bucket "e" iff 2**(e-1) <= v < 2**e.  The clamped end
            # buckets say so explicitly — "<=-32" holds everything below
            # 2**-32 (including non-positive values), ">=31" everything
            # from 2**30 up — so a snapshot can never silently claim an
            # out-of-range value sat inside a nominal bucket.
            "buckets": {
                (
                    "<=-32"
                    if i == 0
                    else ">=31"
                    if i == _N_BUCKETS - 1
                    else str(i - _BUCKET_OFFSET)
                ): c
                for i, c in enumerate(self.buckets)
                if c
            },
        }


def estimate_quantiles(
    hist_json: Dict[str, Any], qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> Optional[Dict[str, float]]:
    """p-quantile estimates from a snapshot-format log2-bucket histogram.

    Works on the JSON form (so dumped snapshots and live ones estimate
    identically): each nominal bucket "e" holds values in [2**(e-1), 2**e);
    the estimate is the bucket's geometric midpoint, clamped to the
    histogram's observed [min, max].  The clamped end buckets estimate at
    the observed extreme on their side.  Returns {"p50": v, ...} or None
    for an empty histogram.
    """
    count = hist_json.get("count", 0)
    if not count:
        return None
    vmin, vmax = hist_json["min"], hist_json["max"]

    def bucket_key(item: Tuple[str, int]) -> int:
        k = item[0]
        if k == "<=-32":
            return -(10**6)
        if k == ">=31":
            return 10**6
        return int(k)

    buckets = sorted(hist_json["buckets"].items(), key=bucket_key)
    out: Dict[str, float] = {}
    for q in qs:
        target = q * count
        cum = 0
        est = vmax
        for k, c in buckets:
            cum += c
            if cum >= target:
                if k == "<=-32":
                    est = vmin
                elif k == ">=31":
                    est = vmax
                else:
                    est = 2.0 ** (int(k) - 0.5)  # geometric bucket midpoint
                break
        # %g keeps the label faithful to the requested quantile: 0.5 ->
        # "p50", 0.29 -> "p29" (int() would float-truncate to "p28"),
        # 0.999 -> "p99.9" (distinct from "p99", no silent collision).
        out["p%g" % (q * 100)] = min(max(est, vmin), vmax)
    return out


class Registry:
    """Thread-safe metrics store.  One process-wide instance lives in this
    module; tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}

    def counter(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.observe(value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_json() for k, h in self._hists.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class _Tracer:
    """Chrome trace-event JSONL writer (buffered, lock-guarded)."""

    _FLUSH_EVERY = 512

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._file: Optional[Any] = open(path, "w")
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "tid": 0,
                "args": {"name": "peritext-tpu"},
            }
        )

    def _emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, separators=(",", ":"))
        with self._lock:
            if self._file is None:
                return
            self._buf.append(line)
            if len(self._buf) >= self._FLUSH_EVERY:
                self._flush_locked()

    def emit_complete(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        tid: int,
        args: Optional[Dict[str, Any]],
    ) -> None:
        event: Dict[str, Any] = {
            "name": name,
            "cat": "peritext",
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            event["args"] = args
        self._emit(event)

    def emit_flow(
        self,
        name: str,
        phase: str,
        flow_id: int,
        ts_us: float,
        tid: int,
        args: Optional[Dict[str, Any]],
    ) -> None:
        """One Chrome flow event (ph s/t/f).  Binding rule: the event
        attaches to the slice covering (pid, tid, ts) — callers emit from
        inside an open span, whose complete event (written later, at span
        exit) covers this timestamp."""
        event: Dict[str, Any] = {
            "name": name,
            "cat": "peritext.flow",
            "ph": phase,
            "id": flow_id,
            "ts": ts_us,
            "pid": os.getpid(),
            "tid": tid,
        }
        if phase == "f":
            # Bind the finish to the ENCLOSING slice (the default binds to
            # the next slice that begins, which here would be arbitrary).
            event["bp"] = "e"
        if args:
            event["args"] = args
        self._emit(event)

    def _flush_locked(self) -> None:
        if self._buf and self._file is not None:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


class _NullSpan:
    """The disabled-path span: a shared, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.args = args
        self._t0 = 0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter_ns()
        # The span may outlive a disable() (e.g. a test tearing down while a
        # timer-thread flush is mid-span); record into whatever plane is
        # current — the registry/tracer never become invalid, only unused.
        secs = (t1 - self._t0) / 1e9
        hist_name = "span." + self.name + ".seconds"
        _registry.observe(hist_name, secs)
        sinks = _observe_sinks
        if sinks is not None:
            fn = sinks.get(hist_name)
            if fn is not None:
                fn(secs)
        tracer = _tracer
        if tracer is not None:
            tracer.emit_complete(
                self.name,
                self._t0 / 1e3,
                (t1 - self._t0) / 1e3,
                threading.get_ident(),
                self.args,
            )
        return False


# -- causal flow contexts -----------------------------------------------------

# Monotonic flow ids: allocation order IS causal mint order, deterministic
# given call order (no Date.now()-style wall entropy), and distinct across
# every plane in the process.
_flow_ids = itertools.count(1)
_flow_lock = threading.Lock()
_tls = threading.local()


class TraceContext:
    """One change-batch's causal identity, threaded across seams.

    ``id`` is the Chrome flow-event id; ``kind`` names the lane (the flow
    events' shared name); ``t0_ns`` is the mint time (perf_counter), so the
    terminal seam can feed the e2e latency histograms.  The phase machine
    (unstarted -> started -> finished) makes emission idempotent-safe: the
    first :func:`flow_point` emits ``s``, later ones ``t``, the terminal
    one ``f``, and anything after a finish is ignored — a retried flush
    cannot corrupt the triplet.
    """

    __slots__ = ("id", "kind", "t0_ns", "meta", "_phase", "_keep")

    def __init__(self, kind: str, meta: Optional[Dict[str, Any]] = None) -> None:
        self.id = next(_flow_ids)
        self.kind = kind
        self.t0_ns = time.perf_counter_ns()
        self.meta = meta
        self._phase = 0  # 0 unstarted, 1 started, 2 finished
        self._keep = False  # explicit tail-keep mark (flow_keep)


class _Flowing:
    """Scoped thread-local flow propagation (replace semantics: the inner
    scope's lanes are what downstream seams join)."""

    __slots__ = ("ctxs", "prev")

    def __init__(self, ctxs: Tuple["TraceContext", ...]) -> None:
        self.ctxs = ctxs
        self.prev: Tuple["TraceContext", ...] = ()

    def __enter__(self) -> "_Flowing":
        self.prev = getattr(_tls, "flows", ())
        _tls.flows = self.ctxs
        return self

    def __exit__(self, *exc: Any) -> bool:
        _tls.flows = self.prev
        return False


# -- flight recorder ----------------------------------------------------------


class _FlightRecorder:
    """Fixed-capacity ring of recent structured events.

    Preallocated slots, one lock, O(1) per record; overwrites count as
    ``dropped`` so post-mortems know how much history the ring held vs
    lost.  Never grows — the always-on cost is bounded by construction.
    """

    __slots__ = ("cap", "buf", "n", "dropped", "lock")

    def __init__(self, cap: int) -> None:
        self.cap = max(1, cap)
        self.buf: List[Any] = [None] * self.cap
        self.n = 0
        self.dropped = 0
        self.lock = threading.Lock()

    def record(
        self,
        t_us: float,
        site: str,
        flow_id: Optional[int],
        outcome: str,
        fields: Optional[Dict[str, Any]],
    ) -> None:
        with self.lock:
            if self.n >= self.cap:
                self.dropped += 1
            self.buf[self.n % self.cap] = (t_us, site, flow_id, outcome, fields)
            self.n += 1

    def events(self) -> List[Dict[str, Any]]:
        with self.lock:
            if self.n <= self.cap:
                items = list(self.buf[: self.n])
            else:
                i = self.n % self.cap
                items = list(self.buf[i:]) + list(self.buf[:i])
        out = []
        for t_us, site, flow_id, outcome, fields in items:
            event: Dict[str, Any] = {"ts_us": t_us, "site": site, "outcome": outcome}
            if flow_id is not None:
                event["flow"] = flow_id
            if fields:
                event["fields"] = fields
            out.append(event)
        return out


class _MetricsFlusher(threading.Thread):
    """Periodic metrics-snapshot + status flush (PERITEXT_METRICS_INTERVAL
    / PERITEXT_STATUS): the atexit dump dies exactly when it matters most
    (SIGKILLed bench child, wedged-relay timeout); this daemon leaves a
    recent atomic snapshot — and the live ops status surface — behind
    instead.  Each tick writes whichever of the metrics/status paths are
    configured."""

    def __init__(self, interval: float) -> None:
        super().__init__(daemon=True, name="peritext-metrics-flusher")
        self.interval = interval
        self.stop_event = threading.Event()

    def run(self) -> None:
        while not self.stop_event.wait(self.interval):
            try:
                dump_metrics()
            except Exception:  # a full disk must not kill the flusher
                import logging

                logging.getLogger(__name__).warning(
                    "periodic metrics flush failed", exc_info=True
                )
            try:
                dump_status()
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "periodic status flush failed", exc_info=True
                )


# -- the process-wide plane ---------------------------------------------------

_registry = Registry()
_tracer: Optional[_Tracer] = None
_metrics_path: Optional[str] = None
_config_lock = threading.Lock()
_atexit_registered = False
_recorder: Optional[_FlightRecorder] = None
_blackbox_dir: Optional[str] = None
_blackbox_seq = itertools.count(1)
_MAX_BLACKBOX_DUMPS = 32
_flusher: Optional[_MetricsFlusher] = None
# Per-reason black-box dump rate limiting (satellite of ISSUE 13): one
# dump per dedupe key per cooldown, so a breach/trip storm cannot exhaust
# the 32-dump cap before the interesting dump.  Keyed by reason (or an
# explicit dedupe key), judged on time.monotonic.
_dump_last: Dict[str, float] = {}
_DUMP_COOLDOWN_DEFAULT = 30.0

# -- tail-sampled tracing state ----------------------------------------------
# Head-sampling probability per flow lane (1.0 = emit everything directly,
# the historical behavior).  Below 1.0 the flow plane buffers lanes in
# _lane_buf and judges them at the terminal seam against the tail rules.
_sample_p = 1.0
_sample_seed = 0
_tail_slow_us: Optional[float] = None  # keep lanes slower than this
_tail_error = False  # keep lanes that touched an error/retry/degrade seam
_tail_breach = False  # keep lanes terminating while an SLO breach is active
_breach_probe: Optional[Callable[[], bool]] = None  # set by the SLO plane
# flow id -> [buffered emit_flow arg tuples, interesting flag].  Bounded:
# past _LANE_BUF_CAP open lanes the oldest is evicted (trace.lanes_evicted).
_lane_buf: Dict[int, List[Any]] = {}
_LANE_BUF_CAP = 4096

# -- SLO feed sinks -----------------------------------------------------------
# Installed by peritext_tpu.runtime.slo: metric-name -> feed callable.
# None (the common case) costs one module-attribute load per enabled call.
_observe_sinks: Optional[Dict[str, Callable[[float], None]]] = None
_counter_sinks: Optional[Dict[str, Callable[[int], None]]] = None

# -- status surface -----------------------------------------------------------
_status_path: Optional[str] = None
# (kind, WeakMethod) pairs registered by live planes (serve, serve_shard);
# dead refs are pruned on read.
_status_sources: List[Tuple[str, Any]] = []


def counter(name: str, n: int = 1) -> None:
    """Add ``n`` to a monotonic counter (no-op while disabled).  With an
    SLO plan installed, names it watches also feed its evaluators."""
    if enabled:
        _registry.counter(name, n)
        sinks = _counter_sinks
        if sinks is not None:
            fn = sinks.get(name)
            if fn is not None:
                fn(n)


def gauge(name: str, value: float) -> None:
    """Set a last-value gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge(name, value)


def gauge_max(name: str, value: float) -> None:
    """Raise a high-water-mark gauge (no-op while disabled)."""
    if enabled:
        _registry.gauge_max(name, value)


def observe(name: str, value: float) -> None:
    """Record a value into a log2-bucket histogram (no-op while disabled).
    With an SLO plan installed, names it watches also feed its
    evaluators."""
    if enabled:
        _registry.observe(name, value)
        sinks = _observe_sinks
        if sinks is not None:
            fn = sinks.get(name)
            if fn is not None:
                fn(value)


def span(name: str, **args: Any) -> Any:
    """Context manager timing a region.  Disabled: returns a shared no-op
    singleton (zero allocation).  Enabled: records a ``span.<name>.seconds``
    histogram entry and, when tracing, a Chrome complete event."""
    if not enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def flow(kind: str, **meta: Any) -> Optional[TraceContext]:
    """Mint a causal trace context (None while disabled — call sites keep
    the one-attr-check contract by guarding on ``telemetry.enabled``).
    ``meta`` rides on the flow's start event (change ids, actor, count)."""
    if not enabled:
        return None
    return TraceContext(kind, meta or None)


# Terminal/step args that mark a lane tail-interesting: failed or rejected
# outcomes, the oracle-degrade seam, and retry attempts (attempt >= 1).
# Every terminal failure outcome any seam emits belongs here — a missed
# spelling silently drops exactly the lanes a post-mortem needs (the
# emitters: serve resolve/shed/close, ingest launch/record, TpuDoc local
# rollback, stream sweep abort, queue drop).
_TAIL_BAD_OUTCOMES = frozenset(
    (
        "error",
        "rejected",
        "shed",
        "closed",
        "fail",
        "fastfail",
        "degraded",
        "rollback",
        "abort",
        "dropped",
    )
)


def _args_interesting(args: Optional[Dict[str, Any]]) -> bool:
    if not args:
        return False
    if args.get("outcome") in _TAIL_BAD_OUTCOMES:
        return True
    if args.get("path") == "degrade":
        return True
    attempt = args.get("attempt")
    return isinstance(attempt, int) and attempt >= 1


def _head_sampled(flow_id: int) -> bool:
    """Deterministic head-sampling verdict for one lane: a seeded hash of
    the flow id (mint order is deterministic under seeded chaos, so the
    same run keeps the same lanes)."""
    p = _sample_p
    if p >= 1.0:
        return True
    if p <= 0.0:
        return False
    return random.Random(f"{_sample_seed}/{flow_id}").random() < p


def flow_point(
    ctx: Optional[TraceContext], terminal: bool = False, **args: Any
) -> None:
    """Mark the current seam on a flow's lane (no-op for None / no tracer).

    MUST be called from inside an open :func:`span` — flow events bind to
    the slice covering their timestamp on this thread.  The first point
    emits the flow start (``s``), later ones steps (``t``), and
    ``terminal=True`` the finish (``f``); points after a finish are
    dropped, so retried seams cannot emit a second finish.

    With head sampling below 1.0 (:func:`set_trace_sampling`) the lane's
    events buffer instead, and the terminal point decides: head-sampled
    in, or retained by a tail rule (slow / error / breach-coincident), the
    whole lane flushes to the tracer; otherwise it drops
    (``trace.lanes_kept`` / ``trace.lanes_dropped``)."""
    if ctx is None:
        return
    tracer = _tracer
    if tracer is None:
        return
    now_us = time.perf_counter_ns() / 1e3
    with _flow_lock:
        phase0 = ctx._phase
        if phase0 == 2:
            return
        start = phase0 == 0
        ctx._phase = 2 if terminal else 1
    tid = threading.get_ident()
    events: List[Tuple[Any, ...]] = []
    if start:
        events.append((ctx.kind, "s", ctx.id, now_us, tid, ctx.meta))
    if terminal:
        events.append((ctx.kind, "f", ctx.id, now_us, tid, args or None))
    elif not start:
        events.append((ctx.kind, "t", ctx.id, now_us, tid, args or None))
    # Direct emission (the historical path) unless sampling is on.  The
    # `_lane_buf` check keeps a lane that STARTED buffered coherent if
    # sampling is reconfigured mid-lane: its remaining points keep
    # buffering, and the terminal verdict (p=1 head-samples everything in)
    # emits the whole lane.
    if _sample_p >= 1.0 and not _lane_buf:
        for ev in events:
            tracer.emit_flow(*ev)
        return
    _buffer_flow(tracer, ctx, events, args or None, terminal)


def _buffer_flow(
    tracer: "_Tracer",
    ctx: TraceContext,
    events: List[Tuple[Any, ...]],
    args: Optional[Dict[str, Any]],
    terminal: bool,
) -> None:
    keep = None
    with _flow_lock:
        buf = _lane_buf.get(ctx.id)
        if buf is None:
            if len(_lane_buf) >= _LANE_BUF_CAP:
                # Evict the oldest still-open lane (insertion order): a
                # leak of never-terminated lanes must not grow unbounded.
                oldest = next(iter(_lane_buf))
                del _lane_buf[oldest]
                if enabled:
                    _registry.counter("trace.lanes_evicted")
            buf = _lane_buf[ctx.id] = [events, False]
        else:
            buf[0].extend(events)
        if args is not None and _args_interesting(args):
            buf[1] = True
        if not terminal:
            return
        lane_events, interesting = _lane_buf.pop(ctx.id)
        interesting = interesting or ctx._keep
        keep = _head_sampled(ctx.id)
        if not keep and _tail_error and interesting:
            keep = True
        if not keep and _tail_slow_us is not None:
            keep = (time.perf_counter_ns() - ctx.t0_ns) / 1e3 >= _tail_slow_us
        if not keep and _tail_breach:
            probe = _breach_probe
            if probe is not None:
                try:
                    keep = bool(probe())
                except Exception:
                    keep = True  # a broken probe must not drop evidence
    # Emission + counters outside _flow_lock (the tracer has its own lock).
    if keep:
        for ev in lane_events:
            tracer.emit_flow(*ev)
    if enabled:
        _registry.counter("trace.lanes_kept" if keep else "trace.lanes_dropped")


def flow_keep(ctx: Optional[TraceContext] = None) -> None:
    """Explicitly mark a lane (default: every lane scoped onto this
    thread) as tail-interesting, guaranteeing retention under tail
    sampling's ``error`` rule regardless of what args its seams carried.
    The degrade/fast-fail seams call this so a sampled production trace
    can never lose a failed lane.  No-op while disabled."""
    if not enabled:
        return
    if ctx is not None:
        ctx._keep = True
        return
    for c in getattr(_tls, "flows", ()):
        c._keep = True


def set_trace_sampling(
    sample: Optional[float] = None,
    tail: Optional[str] = None,
    seed: Optional[int] = None,
) -> None:
    """Configure flow-lane sampling (``PERITEXT_TRACE_SAMPLE`` /
    ``PERITEXT_TRACE_TAIL`` / ``PERITEXT_TRACE_SAMPLE_SEED``).

    ``sample`` is the head-sampling probability per lane, clamped to
    [0, 1]; 1.0 restores direct emission.  ``tail`` is a ``|``-separated
    rule list — ``slow:<ms>`` (keep lanes at least that slow), ``error``
    (keep lanes that touched an error/retry/degrade seam or were
    :func:`flow_keep`-marked), ``breach`` (keep lanes terminating while an
    SLO breach is active); the empty string clears the rules.  Unknown
    rules raise ValueError (a typo'd spec must not silently sample
    everything away)."""
    global _sample_p, _sample_seed, _tail_slow_us, _tail_error, _tail_breach
    with _config_lock:
        if sample is not None:
            _sample_p = min(1.0, max(0.0, float(sample)))
        if seed is not None:
            _sample_seed = int(seed)
        if tail is not None:
            slow_us: Optional[float] = None
            error = breach = False
            for term in tail.split("|"):
                term = term.strip()
                if not term:
                    continue
                if term.startswith("slow:"):
                    slow_us = float(term[5:]) * 1e3
                elif term == "error":
                    error = True
                elif term == "breach":
                    breach = True
                else:
                    raise ValueError(
                        f"unknown trace tail rule {term!r} "
                        "(want slow:<ms> | error | breach)"
                    )
            _tail_slow_us = slow_us
            _tail_error = error
            _tail_breach = breach


def sampling_active() -> bool:
    """True when flow lanes are being buffered and judged (head sampling
    below 1.0), False in direct-emission mode."""
    return _sample_p < 1.0


def flow_steps(terminal: bool = False, **args: Any) -> None:
    """flow_point for every lane propagated onto this thread (deep seams —
    ingest attempts, degradation, readback — join whatever lanes the
    enclosing flush/change/delivery scoped in via :func:`flowing`)."""
    for ctx in getattr(_tls, "flows", ()):
        flow_point(ctx, terminal=terminal, **args)


def flowing(ctxs: Sequence[Optional[TraceContext]]) -> Any:
    """Scope flow contexts onto this thread for downstream seams.  Returns
    an allocation-free no-op for an empty/None-only sequence, so disabled
    call sites pay nothing."""
    live = tuple(c for c in ctxs if c is not None)
    if not live:
        return _NULL_SPAN
    return _Flowing(live)


def current_flows() -> Tuple[TraceContext, ...]:
    """The lanes scoped onto this thread (empty tuple when none)."""
    return getattr(_tls, "flows", ())


def current_flow() -> Optional[TraceContext]:
    """The first lane scoped onto this thread, or None — the one to stamp
    on single-flow recorder events."""
    flows = getattr(_tls, "flows", ())
    return flows[0] if flows else None


def flow_elapsed_s(ctx: TraceContext) -> float:
    """Seconds since the context was minted (feeds the e2e histograms)."""
    return (time.perf_counter_ns() - ctx.t0_ns) / 1e9


def record(
    site: str,
    flow: Optional[TraceContext] = None,
    outcome: str = "ok",
    **fields: Any,
) -> None:
    """Append one structured event to the flight-recorder ring (no-op
    while disabled).  Launch-level granularity, like every other site."""
    if not enabled:
        return
    rec = _recorder
    if rec is None:
        rec = _ensure_recorder()
    rec.record(
        time.perf_counter_ns() / 1e3,
        site,
        None if flow is None else flow.id,
        outcome,
        fields or None,
    )


def _ensure_recorder() -> _FlightRecorder:
    global _recorder
    with _config_lock:
        if _recorder is None:
            try:
                cap = int(os.environ.get("PERITEXT_BLACKBOX_RING", "512") or 512)
            except ValueError:
                cap = 512
            _recorder = _FlightRecorder(cap)
        return _recorder


def recorder_events() -> List[Dict[str, Any]]:
    """The ring's events, oldest first (empty when nothing recorded)."""
    rec = _recorder
    return [] if rec is None else rec.events()


def recorder_stats() -> Tuple[int, int]:
    """(events recorded, events dropped by ring overwrite)."""
    rec = _recorder
    return (0, 0) if rec is None else (rec.n, rec.dropped)


def blackbox_dir() -> Optional[str]:
    """The armed black-box dump directory, or None."""
    return _blackbox_dir


def blackbox_dump(
    reason: str,
    dedupe_key: Optional[str] = None,
    dedupe_cooldown_s: Optional[float] = None,
    **info: Any,
) -> Optional[str]:
    """Write a post-mortem dump (ring + registry snapshot + summary) to the
    ``PERITEXT_BLACKBOX`` directory; returns the path or None when unarmed.

    Atomic (tmp+rename), monotonic per-process sequence numbers, and capped
    at a few dozen dumps per process so a wedge storm cannot fill the disk
    (skips count as ``blackbox.skipped``).  Additionally rate-limited per
    reason: within ``dedupe_cooldown_s`` (default
    ``PERITEXT_BLACKBOX_COOLDOWN``, 30s) of the previous dump for the same
    ``dedupe_key`` (default: the reason), the dump is skipped and counted
    as ``blackbox.deduped`` — a trip/breach storm writes its first dump,
    not 32 copies of it.  Callers that rate-limit themselves (the SLO
    plane, on its injectable clock) pass ``dedupe_cooldown_s=0`` to bypass
    the wall-clock limiter.  Never raises
    — a full disk must not turn a post-mortem into a second failure."""
    d = _blackbox_dir
    if d is None:
        return None
    if dedupe_cooldown_s is None:
        try:
            dedupe_cooldown_s = float(
                os.environ.get("PERITEXT_BLACKBOX_COOLDOWN", "")
                or _DUMP_COOLDOWN_DEFAULT
            )
        except ValueError:
            dedupe_cooldown_s = _DUMP_COOLDOWN_DEFAULT
    key = dedupe_key or reason
    now = time.monotonic()
    with _config_lock:
        last = _dump_last.get(key)
        if (
            last is not None
            and dedupe_cooldown_s > 0
            and now - last < dedupe_cooldown_s
        ):
            if enabled:
                _registry.counter("blackbox.deduped")
            return None
        _dump_last[key] = now
    seq = next(_blackbox_seq)
    if seq > _MAX_BLACKBOX_DUMPS:
        if enabled:
            _registry.counter("blackbox.skipped")
        return None
    rec = _recorder
    payload = {
        "reason": reason,
        "info": info,
        "pid": os.getpid(),
        "ring": [] if rec is None else rec.events(),
        "ring_dropped": 0 if rec is None else rec.dropped,
        "metrics": snapshot(),
        "summary": summary(),
    }
    path = os.path.join(d, f"blackbox-{os.getpid()}-{seq:04d}-{reason}.json")
    tmp = path + ".tmp"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        import logging

        logging.getLogger(__name__).warning(
            "black-box dump to %r failed", path, exc_info=True
        )
        return None
    if enabled:
        _registry.counter("blackbox.dumps")
    flush_trace()  # the trace should cover everything the dump names
    return path


def snapshot() -> Dict[str, Any]:
    """Full registry contents: {"counters", "gauges", "histograms"}."""
    return _registry.snapshot()


def summary() -> Dict[str, Any]:
    """Compact well-known subset for bench lines and chaos-run footers:
    launch/retry/degradation tallies, merge-path choices, queue depth,
    traffic bytes, and the mirrored fault counters.  Only keys that saw
    traffic appear, so the summary stays one short JSON object."""
    snap = _registry.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    out: Dict[str, Any] = {}
    for key, src in (
        ("launches", "ingest.launches"),
        ("launch_attempts", "ingest.launch_attempts"),
        ("launch_retries", "ingest.launch_retries"),
        ("launch_failures", "ingest.launch_failures"),
        ("degraded_batches", "ingest.degraded_batches"),
        ("h2d_bytes", "ingest.h2d_bytes"),
        ("d2h_bytes", "ingest.d2h_bytes"),
        ("queue_flushes", "queue.flushes"),
        ("queue_reenqueues", "queue.reenqueues"),
        ("queue_shed", "queue.shed"),
        ("queue_coalesced", "queue.coalesced"),
        ("queue_blocked", "queue.blocked"),
        ("sync_deferred", "sync.deferred"),
        ("health_fastfails", "health.fastfail"),
        ("pubsub_delivered", "pubsub.delivered"),
        ("stream_cohorts", "stream.cohorts"),
        ("checkpoint_corrupt_fallbacks", "checkpoint.corrupt_fallbacks"),
        ("local_gen_rollbacks", "doc.local_gen_rollbacks"),
        ("blackbox_dumps", "blackbox.dumps"),
        ("blackbox_skipped", "blackbox.skipped"),
        ("window_fallbacks", "ingest.window_fallbacks"),
        ("window_rebuilds", "ingest.window_rebuilds"),
        ("blackbox_deduped", "blackbox.deduped"),
        ("trace_lanes_kept", "trace.lanes_kept"),
        ("trace_lanes_dropped", "trace.lanes_dropped"),
        ("trace_lanes_evicted", "trace.lanes_evicted"),
    ):
        if src in counters:
            out[key] = counters[src]
    paths = {
        name.rsplit(".", 1)[1]: n
        for name, n in counters.items()
        if name.startswith("ingest.path.")
    }
    if paths:
        out["merge_path"] = paths
    if "queue.depth_max" in gauges:
        out["queue_depth_max"] = gauges["queue.depth_max"]
    if "stream.inflight_max" in gauges:
        out["stream_inflight_max"] = gauges["stream.inflight_max"]
    faults_mirror = {
        name[len("faults.") :]: n
        for name, n in counters.items()
        if name.startswith("faults.")
    }
    if faults_mirror:
        out["faults"] = faults_mirror
    health_mirror = {
        name[len("health.") :]: n
        for name, n in counters.items()
        if name.startswith("health.") and name != "health.fastfail"
    }
    if health_mirror:
        out["health"] = health_mirror
    # SLO-plane mirror (runtime/slo.py): breach counters plus the live
    # burn/compliance/breached gauges, so a bench stamp or chaos footer
    # carries the objective verdicts without a separate plumbing path.
    slo_mirror: Dict[str, Any] = {
        name[len("slo.") :]: n
        for name, n in counters.items()
        if name.startswith("slo.")
    }
    for name, v in gauges.items():
        if name.startswith("slo."):
            slo_mirror[name[len("slo.") :]] = v
    if slo_mirror:
        out["slo"] = slo_mirror
    # Serving-plane tallies (runtime/serve.py): present whenever serve
    # traffic happened, so bench JSON stamps and the fuzz --chaos footer
    # carry admission/batching/shed behavior without a separate plumbing
    # path.  The e2e.admit_to_applied percentiles ride in out["e2e"].
    serve_mirror = {
        name[len("serve.") :]: n
        for name, n in counters.items()
        if name.startswith("serve.")
    }
    if serve_mirror:
        if "serve.depth_max" in gauges:
            serve_mirror["depth_max"] = gauges["serve.depth_max"]
        out["serve"] = serve_mirror
    # Autoscaler tallies (runtime/elastic.py): migrations / rollbacks /
    # parked-delivery counts ride bench stamps and the fuzz footer the
    # same way the serve block does.
    elastic_mirror = {
        name[len("elastic.") :]: n
        for name, n in counters.items()
        if name.startswith("elastic.")
    }
    if elastic_mirror:
        out["elastic"] = elastic_mirror
    # End-to-end latency percentiles (the causal-flow plane's terminal
    # seams) + the key per-seam latencies, estimated from the log2
    # histograms — the "why was p99 40x the median" numbers a one-line
    # bench stamp or chaos footer can carry.
    hists = snap["histograms"]
    e2e = {}
    for name, h in hists.items():
        if name.startswith("e2e."):
            q = estimate_quantiles(h)
            if q is not None:
                q["count"] = h["count"]
                e2e[name[len("e2e.") :]] = q
    if e2e:
        out["e2e"] = e2e
    lat = {}
    for label, src in (
        ("ingest_launch_s", "span.ingest.launch_attempt.seconds"),
        ("queue_flush_s", "queue.flush_seconds"),
    ):
        if src in hists:
            q = estimate_quantiles(hists[src])
            if q is not None:
                lat[label] = q
    if lat:
        out["latency"] = lat
    rec_n, rec_dropped = recorder_stats()
    if rec_n:
        out["recorder_events"] = rec_n
        out["recorder_dropped"] = rec_dropped
    return out


def _install_slo_sinks(
    observe_map: Optional[Dict[str, Callable[[float], None]]],
    counter_map: Optional[Dict[str, Callable[[int], None]]],
    breach_probe: Optional[Callable[[], bool]],
) -> None:
    """Wire (or clear, with Nones) the SLO plane's feed maps and breach
    probe.  Called by :mod:`peritext_tpu.runtime.slo` on install/reset —
    not a public API."""
    global _observe_sinks, _counter_sinks, _breach_probe
    with _config_lock:
        _observe_sinks = observe_map or None
        _counter_sinks = counter_map or None
        _breach_probe = breach_probe


def register_status_source(kind: str, method: Any) -> None:
    """Register a live plane's status contributor (a *bound method*
    returning a JSON-able dict; held as a weakref, so a dropped plane
    silently leaves the surface).  ``kind`` groups the payload in
    :func:`status` — the serving planes register ``"serve"`` /
    ``"serve_shards"``."""
    ref = weakref.WeakMethod(method)
    with _config_lock:
        # Opportunistic prune: long test sessions mint many short-lived
        # planes; dead refs must not accumulate.
        _status_sources[:] = [(k, r) for k, r in _status_sources if r() is not None]
        _status_sources.append((kind, ref))


def status() -> Dict[str, Any]:
    """One operator-facing snapshot of the live process: breaker states,
    queue pressure, serving-plane occupancy (per-session lane depth +
    deficit, per-shard width/occupancy + fleet compiled-shape pressure),
    windowed-merge engagement, per-SLO compliance/burn, e2e latency
    quantiles, and the trace sampler's verdict counts.  Built entirely
    from already-collected state — calling it never perturbs the planes
    it reports on.  ``PERITEXT_STATUS=<path>`` writes it periodically
    (and at exit); ``scripts/ops_top.py`` renders it."""
    snap = _registry.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "time": time.time(),
        "enabled": enabled,
    }
    # Health plane: breaker state + tallies per site.  Late import — the
    # health module imports this one, so the dependency must stay one-way
    # at import time.
    try:
        from peritext_tpu.runtime import health as _health

        breakers = _health.summary()
    except Exception:
        breakers = {}
    if breakers:
        out["breakers"] = breakers
    queue_block: Dict[str, Any] = {}
    for label, src in (
        ("flushes", "queue.flushes"),
        ("reenqueues", "queue.reenqueues"),
        ("shed", "queue.shed"),
        ("coalesced", "queue.coalesced"),
        ("blocked", "queue.blocked"),
    ):
        if src in counters:
            queue_block[label] = counters[src]
    if "queue.depth_max" in gauges:
        queue_block["depth_max"] = gauges["queue.depth_max"]
    if queue_block:
        out["queue"] = queue_block
    launches = counters.get("ingest.launches", 0)
    if launches:
        windowed = counters.get("ingest.path.windowed", 0)
        out["ingest"] = {
            "launches": launches,
            "degraded_batches": counters.get("ingest.degraded_batches", 0),
            "launch_failures": counters.get("ingest.launch_failures", 0),
            "fastfails": counters.get("health.fastfail", 0),
            "windowed_launches": windowed,
            "window_engagement_pct": round(100.0 * windowed / launches, 1),
            "window_fallbacks": counters.get("ingest.window_fallbacks", 0),
        }
    # Live plane contributors (serve / serve_shard status sources).
    with _config_lock:
        sources = list(_status_sources)
    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for kind, ref in sources:
        method = ref()
        if method is None:
            continue
        try:
            payload = method()
        except Exception:  # a closing plane must not break the surface
            continue
        if payload:
            by_kind.setdefault(kind, []).append(payload)
    for kind, payloads in by_kind.items():
        out[kind] = payloads
    # SLO plane verdicts (late import, same one-way rationale as health).
    try:
        from peritext_tpu.runtime import slo as _slo

        slo_summary = _slo.summary()
    except Exception:
        slo_summary = {}
    if slo_summary:
        out["slo"] = slo_summary
    e2e = {}
    for name, h in snap["histograms"].items():
        if name.startswith("e2e."):
            q = estimate_quantiles(h)
            if q is not None:
                q["count"] = h["count"]
                e2e[name[len("e2e.") :]] = q
    if e2e:
        out["e2e"] = e2e
    trace_block: Dict[str, Any] = {}
    if _tracer is not None:
        trace_block["path"] = _tracer.path
    if sampling_active():
        trace_block["sample"] = _sample_p
        trace_block["tail"] = {
            "slow_ms": None if _tail_slow_us is None else _tail_slow_us / 1e3,
            "error": _tail_error,
            "breach": _tail_breach,
        }
        with _flow_lock:
            trace_block["open_lanes"] = len(_lane_buf)
    for label, src in (
        ("lanes_kept", "trace.lanes_kept"),
        ("lanes_dropped", "trace.lanes_dropped"),
        ("lanes_evicted", "trace.lanes_evicted"),
    ):
        if src in counters:
            trace_block[label] = counters[src]
    if trace_block:
        out["trace"] = trace_block
    for label, src in (
        ("blackbox_dumps", "blackbox.dumps"),
        ("blackbox_deduped", "blackbox.deduped"),
    ):
        if src in counters:
            out[label] = counters[src]
    return out


def dump_status(path: Optional[str] = None) -> Optional[str]:
    """Write :func:`status` as JSON, atomically (tmp+rename, per-writer tmp
    names — same discipline as :func:`dump_metrics`).  Defaults to the
    ``PERITEXT_STATUS`` path; returns the path written or None."""
    path = path or _status_path
    if not path:
        return None
    payload = status()
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with _dump_lock:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    return path


def trace_path() -> Optional[str]:
    """Path of the active trace file, or None when not tracing."""
    tracer = _tracer
    return None if tracer is None else tracer.path


def enable(
    trace: Optional[str] = None,
    metrics: Optional[str] = None,
    blackbox: Optional[str] = None,
    metrics_interval: Optional[float] = None,
    status_path: Optional[str] = None,
) -> None:
    """Turn collection on.  ``trace`` opens (truncating) a Chrome trace
    JSONL file; ``metrics`` schedules a snapshot dump at interpreter exit
    (``metrics_interval`` > 0 additionally flushes it periodically from a
    daemon thread); ``blackbox`` arms failure dumps to a directory;
    ``status_path`` arms the live ops status surface (written on the same
    periodic flusher and at exit).  All may be omitted — a bare
    ``enable()`` collects registry metrics only."""
    global enabled, _tracer, _metrics_path, _blackbox_dir, _flusher, _status_path
    with _config_lock:
        if trace:
            if _tracer is not None and _tracer.path != trace:
                _tracer.close()
                _tracer = None
            if _tracer is None:
                _tracer = _Tracer(trace)
        if metrics:
            _metrics_path = metrics
        if blackbox:
            _blackbox_dir = blackbox
        if status_path:
            _status_path = status_path
        _ensure_atexit_locked()
        enabled = True
        if (
            metrics_interval
            and metrics_interval > 0
            and (_metrics_path or _status_path)
        ):
            if _flusher is not None and _flusher.interval != metrics_interval:
                _flusher.stop_event.set()
                _flusher = None
            if _flusher is None:
                _flusher = _MetricsFlusher(metrics_interval)
                _flusher.start()


def disable() -> None:
    """Stop collection (registry contents and the trace file are kept —
    re-enable resumes into them; use :func:`reset` for a pristine plane)."""
    global enabled
    enabled = False


def reset() -> None:
    """Back to a pristine, disabled plane: counters cleared, tracer closed,
    exit dump canceled, recorder ring dropped, black-box disarmed, the
    periodic flusher stopped, sampling back to direct emission, SLO sinks
    and status sources cleared.  Does NOT re-read the environment (tests
    own the lifecycle after a reset)."""
    global enabled, _tracer, _metrics_path, _recorder, _blackbox_dir, _flusher
    global _sample_p, _sample_seed, _tail_slow_us, _tail_error, _tail_breach
    global _breach_probe, _observe_sinks, _counter_sinks, _status_path
    with _config_lock:
        enabled = False
        if _tracer is not None:
            _tracer.close()
            _tracer = None
        _metrics_path = None
        _recorder = None
        _blackbox_dir = None
        if _flusher is not None:
            _flusher.stop_event.set()
            _flusher = None
        _sample_p = 1.0
        _sample_seed = 0
        _tail_slow_us = None
        _tail_error = _tail_breach = False
        _breach_probe = None
        _observe_sinks = None
        _counter_sinks = None
        _status_path = None
        _status_sources.clear()
        _dump_last.clear()
        _registry.clear()
    with _flow_lock:
        _lane_buf.clear()


def flush_trace() -> None:
    """Force buffered trace events to disk (the tracer also flushes every
    few hundred events and at exit)."""
    tracer = _tracer
    if tracer is not None:
        tracer.flush()


_dump_lock = threading.Lock()


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Write the metrics snapshot (+ summary) as JSON, atomically.
    Defaults to the ``PERITEXT_METRICS`` path; returns the path written or
    None.  Serialized under a lock AND written via a per-writer tmp name:
    the periodic flusher can race the atexit dump (or a programmatic
    call), and two writers sharing one tmp path would rename an
    interleaved file into place — exactly the corrupt snapshot this
    feature exists to prevent."""
    path = path or _metrics_path
    if not path:
        return None
    payload = snapshot()
    payload["summary"] = summary()
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with _dump_lock:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    return path


def _at_exit() -> None:
    try:
        try:
            if _metrics_path:
                dump_metrics(_metrics_path)
        finally:
            if _status_path:
                dump_status(_status_path)
    finally:
        tracer = _tracer
        if tracer is not None:
            tracer.flush()


def _ensure_atexit_locked() -> None:
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(_at_exit)
        _atexit_registered = True


def _activate_from_env() -> None:
    """Import-time activation from PERITEXT_TRACE / PERITEXT_METRICS /
    PERITEXT_BLACKBOX / PERITEXT_STATUS (+ PERITEXT_METRICS_INTERVAL and
    the PERITEXT_TRACE_SAMPLE / PERITEXT_TRACE_TAIL sampler knobs).

    A bad trace path (missing directory, permissions) must not take the
    whole product down at import — observability degrades to untraced
    collection with a warning instead.  Programmatic :func:`enable` still
    raises, so deliberate callers see the real error."""
    trace = os.environ.get("PERITEXT_TRACE")
    metrics = os.environ.get("PERITEXT_METRICS")
    blackbox = os.environ.get("PERITEXT_BLACKBOX")
    status_p = os.environ.get("PERITEXT_STATUS")
    try:
        interval = float(os.environ.get("PERITEXT_METRICS_INTERVAL", "0") or 0)
    except ValueError:
        interval = 0.0
    if status_p and not interval:
        # The status surface is only useful live; give it a cadence even
        # when the metrics snapshot doesn't ask for one.
        interval = 2.0
    sample = os.environ.get("PERITEXT_TRACE_SAMPLE")
    tail = os.environ.get("PERITEXT_TRACE_TAIL")
    seed = os.environ.get("PERITEXT_TRACE_SAMPLE_SEED")
    if sample or tail or seed:
        try:
            set_trace_sampling(
                sample=float(sample) if sample else None,
                tail=tail if tail is not None else None,
                seed=int(seed) if seed else None,
            )
        except ValueError as exc:
            import logging

            logging.getLogger(__name__).warning(
                "trace sampling env unusable (%s); sampling stays off", exc
            )
    if not (trace or metrics or blackbox or status_p):
        return
    try:
        enable(
            trace=trace or None,
            metrics=metrics or None,
            blackbox=blackbox or None,
            metrics_interval=interval or None,
            status_path=status_p or None,
        )
    except OSError as exc:
        import logging

        logging.getLogger(__name__).warning(
            "PERITEXT_TRACE=%r unusable (%s); continuing without a tracer",
            trace,
            exc,
        )
        enable(
            metrics=metrics or None,
            blackbox=blackbox or None,
            metrics_interval=interval or None,
            status_path=status_p or None,
        )


_activate_from_env()
