"""Anti-entropy synchronization between replicas.

Reference: test/merge.ts:4-23 (applyChanges retry loop) and 25-38
(getMissingChanges).  The reference tolerates out-of-order delivery by
retrying causally-unready changes in a queue with a divergence guard; we also
provide :func:`causal_sort`, which topologically orders a batch up front so
the TPU engine can apply it in one pass with no retries — the "pre-sort by
Lamport key + deps check before kernel launch" design (SURVEY.md §2.4).
"""
from __future__ import annotations

import heapq
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.runtime import telemetry

Change = Dict[str, Any]


class ConvergenceError(RuntimeError):
    """``apply_changes`` could not drain its queue: some changes' causal
    dependencies never arrived (or duplicates/forks kept being rejected).

    Carries the still-pending changes (``pending``) and their ``(actor,
    seq)`` ids (``pending_ids``) so chaos-test triage can see exactly which
    deliveries went missing instead of a bare "did not converge".
    """

    def __init__(self, pending: Sequence[Change]):
        self.pending = list(pending)
        self.pending_ids = [(c["actor"], c["seq"]) for c in self.pending]
        ids = ", ".join(f"{a}@{s}" for a, s in self.pending_ids[:8])
        if len(self.pending_ids) > 8:
            ids += f", ... ({len(self.pending_ids) - 8} more)"
        actors = len({a for a, _ in self.pending_ids})
        super().__init__(
            f"apply_changes did not converge; {len(self.pending)} pending "
            f"(actor, seq) id(s) across {actors} actor(s): [{ids}]"
        )


def apply_available(
    doc: Any, changes: Sequence[Change]
) -> tuple[List[Dict[str, Any]], List[Change]]:
    """Apply every causally-ready change; return (patches, still_pending).

    The retry-queue core shared by :func:`apply_changes` and gap-tolerant
    consumers (the Editor's delivery buffer, chaos fuzzing): unready changes
    rotate to the back (reference test/merge.ts:4-23) until a full rotation
    makes no progress, and the unapplied remainder comes back to the caller
    instead of raising.  Already-seen changes (duplicated deliveries) drop
    idempotently — the same rule as the batched engine's causal gate — so a
    retry buffer fed duplicates cannot grow without bound.
    """
    pending = deque(changes)
    patches: List[Dict[str, Any]] = []
    stuck = 0
    # Causal-flow seam: the retry-queue walk runs inside a span so any lane
    # scoped onto this thread (a pubsub delivery, a queue flush) steps
    # through it — this is where a chaotically-delivered change either
    # applies or defers, exactly the fate a per-change trace must show.
    with telemetry.span("sync.apply", changes=len(pending)):
        if telemetry.enabled:
            telemetry.flow_steps()
        while pending:
            change = pending.popleft()
            if change["seq"] <= doc.clock.get(change["actor"], 0):
                continue  # duplicate delivery: already applied
            try:
                patches.extend(doc.apply_change(change))
                stuck = 0
            except ValueError:
                pending.append(change)
                stuck += 1
                if stuck >= len(pending):
                    break
            except Exception as exc:
                # Non-causal failure mid-batch (backend error, malformed
                # change): earlier changes DID apply and their patches must not
                # be lost, but a function cannot both return and raise — tag the
                # exception with the partial progress so consumers with retry
                # buffers (the Editor) can keep it, and put the failing change
                # back at the front for redelivery-free retry.
                pending.appendleft(change)
                exc.applied_patches = patches  # type: ignore[attr-defined]
                exc.unapplied = list(pending)  # type: ignore[attr-defined]
                raise
        if pending and telemetry.enabled:
            # Chaotic-delivery accounting: how many causally-unready changes
            # each gap-tolerant pass handed back (allow_gaps consumers leave
            # them for a later anti-entropy redelivery).
            telemetry.counter("sync.deferred", len(pending))
            telemetry.record("sync.defer", outcome="deferred", count=len(pending))
            telemetry.flow_steps(deferred=len(pending))
    return patches, list(pending)


def apply_changes(
    doc: Any, changes: Sequence[Change], allow_gaps: bool = False
) -> List[Dict[str, Any]]:
    """Apply changes tolerating causal gaps, retrying until convergence.

    Reference test/merge.ts:4-23: unready changes rotate to the back of the
    queue.  Divergence (a full rotation with no progress — genuinely
    missing dependencies) raises :class:`ConvergenceError` carrying the
    still-pending changes.  With ``allow_gaps`` (chaotic-delivery mode:
    drops/dups/reorders are expected and a later anti-entropy sync
    redelivers), the undeliverable remainder is silently left unapplied
    instead.
    """
    patches, pending = apply_available(doc, changes)
    if pending and not allow_gaps:
        raise ConvergenceError(pending)
    return patches


def _is_ready(change: Change, clock: Dict[str, int]) -> bool:
    return clock.get(change["actor"], 0) == change["seq"] - 1 and all(
        clock.get(actor, 0) >= dep
        for actor, dep in (change.get("deps") or {}).items()
    )


def _blocker(change: Change, clock: Dict[str, int]) -> Optional[Tuple[str, int]]:
    """The first unmet readiness condition as a ``(actor, value)`` wake key
    (the change becomes re-checkable when ``clock[actor]`` reaches exactly
    ``value``), or None when the change is ready now.  The clock only ever
    advances in +1 steps per actor during an ordering walk, so it passes
    through every integer it will ever exceed — a key whose value the clock
    is already past can never fire, which is exactly the permanently-stuck
    (duplicate/forked seq) case the callers report as unsatisfiable."""
    if clock.get(change["actor"], 0) != change["seq"] - 1:
        return (change["actor"], change["seq"] - 1)
    for actor, dep in (change.get("deps") or {}).items():
        if clock.get(actor, 0) < dep:
            return (actor, dep)
    return None


def _retry_queue_order(
    items: Sequence[Change], clock: Dict[str, int]
) -> Tuple[List[Change], int]:
    """The retry-queue emission order over ``items`` (positions = list
    order), computed with an indexed ready-set instead of repeated passes.

    Semantics are byte-identical to the reference retry loop (test/merge.ts:
    4-23): scan the remaining changes in order, emitting the ones ready at
    scan time; deferred changes keep their relative order and are rescanned
    on the next pass.  Equivalently: within a pass, a change woken by an
    emission at an *earlier* position still emits this pass; one woken by an
    emission at a *later* position waits for the next pass.  The rotating
    deque pays a full O(n) rescan per emission in the worst case (a reversed
    single-actor chain is O(n^2)); here each change parks on the one unmet
    ``(actor, value)`` condition blocking it and is re-examined only when
    that clock entry lands — O(n + e) parks with an O(log n) heap pop per
    emission.  ``clock`` is mutated in place.  Returns (ordered, leftover
    count of unsatisfiable changes).
    """
    ready: List[int] = []  # current pass, heap by position
    next_ready: List[int] = []  # woken at/before the cursor: next pass
    waiting: Dict[Tuple[str, int], List[int]] = {}

    def park(i: int) -> bool:
        key = _blocker(items[i], clock)
        if key is None:
            return False
        waiting.setdefault(key, []).append(i)
        return True

    for i in range(len(items)):
        if not park(i):
            ready.append(i)
    heapq.heapify(ready)
    ordered: List[Change] = []
    parked = len(items) - len(ready)
    while ready or next_ready:
        if not ready:
            ready = next_ready
            next_ready = []
            heapq.heapify(ready)
        pos = heapq.heappop(ready)
        change = items[pos]
        # Re-check at pop time: a same-(actor, seq) duplicate classified
        # ready earlier is stale once its twin emits (the rotating loop
        # would defer it forever; here it re-parks on an unreachable key).
        if not _is_ready(change, clock):
            parked += park(pos)
            continue
        clock[change["actor"]] = change["seq"]
        ordered.append(change)
        woken = waiting.pop((change["actor"], change["seq"]), None)
        if woken:
            parked -= len(woken)
            for w in woken:
                if _is_ready(items[w], clock):
                    # Later position: still scannable this pass.  Earlier:
                    # already deferred this pass, emits next pass.
                    if w > pos:
                        heapq.heappush(ready, w)
                    else:
                        next_ready.append(w)
                else:
                    parked += park(w)
    return ordered, parked


def causal_order(changes: Sequence[Change], clock: Dict[str, int] | None = None) -> List[Change]:
    """Delivery-order-preserving causal ordering.

    The exact order the reference's applyChanges retry loop (test/merge.ts:
    4-23) would apply a batch in: changes apply in delivery order, with
    causally-unready ones deferred to the back of the queue.  This matters
    beyond correctness: *patch streams are delivery-order-sensitive* (patch
    indices depend on what applied before), so batched engines must use this
    order — not an arbitrary topological sort — to emit the same patches an
    incremental replica would.  O(n + e) via the indexed ready-set walk
    (:func:`_retry_queue_order`); output order is byte-identical to the
    rotating-deque formulation (tests/test_sync_order.py pins it against a
    reference copy of the old loop).
    """
    clock = dict(clock or {})
    ordered, leftover = _retry_queue_order(list(changes), clock)
    if leftover:
        raise ValueError(
            f"causal_order: {leftover} changes have unsatisfiable dependencies"
        )
    return ordered


def causal_sort(changes: Sequence[Change], clock: Dict[str, int] | None = None) -> List[Change]:
    """Order a batch of changes so each one's causal dependencies precede it.

    Kahn's algorithm over the (actor seq-chain + deps) DAG, seeded with the
    receiving replica's current ``clock``.  Ties broken by (startOp, actor)
    for determinism.  Raises ``ValueError`` if the batch has unsatisfiable
    dependencies — the batched-engine analog of the reference's
    causal-readiness throw (micromerge.ts:501-509).  The frontier walk is
    the shared :func:`_retry_queue_order` over the sorted positions, so the
    emission order is byte-identical to the repeated-pass formulation at
    O(n + e) instead of O(n * passes).
    """
    clock = dict(clock or {})
    items = sorted(changes, key=lambda c: (c["startOp"], c["actor"], c["seq"]))
    ordered, leftover = _retry_queue_order(items, clock)
    if leftover:
        raise ValueError(
            f"causal_sort: {leftover} changes have unsatisfiable dependencies"
        )
    return ordered


def sync_pair(log: Any, left: Any, right: Any) -> tuple[list, list]:
    """Anti-entropy sync between two replicas through a shared change log.

    Returns (patches applied to left, patches applied to right).  This is the
    reference fuzzer's sync step (fuzz.ts:181-202).
    """
    to_right = log.missing_changes(left.clock, right.clock)
    to_left = log.missing_changes(right.clock, left.clock)
    right_patches = apply_changes(right, to_right)
    left_patches = apply_changes(left, to_left)
    return left_patches, right_patches
