"""Elastic serving: SLO-driven shard autoscaling with live session migration.

The sharded serving plane (runtime/serve_shard.py) is statically
partitioned at construction — a traffic spike on one shard burns its SLOs
until someone rebuilds the fleet.  This module closes the loop:

- :func:`migrate_session` moves ONE session between shards of a
  :class:`~peritext_tpu.runtime.serve_shard.ShardedServePlane` with zero
  byte-stream disruption.  The protocol: park the session (new deliveries
  buffer), drain the source lane, export the replica row under the source
  plane's flush-quiescence barrier (``runtime/checkpoint.export_replica``),
  provision a row on the target via the pow2 pad plane +
  ``TpuUniverse.rename_replica``, import (masked intern-id remap, digest
  verified), then commit: evict + evacuate the source row, rebind the
  session to a fresh inner lane on the target (the patch log is the SAME
  list object, so the concatenated stream is seamless), graft any
  still-laned submissions, catch up the doc group's log tail, and replay
  the park buffer in order.  Every pre-commit step is a
  ``faults.fire("shard_migrate")`` chokepoint; any failure rolls back —
  the target row unwinds, parked deliveries replay onto the source lane,
  a rate-limited black-box dump fires — and the source shard stays
  authoritative, so a failed migration is invisible to byte-identity.

- :class:`ElasticController` is the autoscaler control loop: it watches
  per-shard load (pending changes + sessions, the same metric the
  ``load`` placement policy uses), fleet compiled-shape pressure, and the
  SLO plane's burn state (:func:`peritext_tpu.runtime.slo.active`), and
  rebalances live — migrating a session off the hottest shard when its
  load spreads past ``PERITEXT_ELASTIC_SPREAD`` times the coldest (or an
  SLO objective is burning), and consolidating a near-idle fleet's
  stragglers into pad rows so shard widths (and compiled shapes) shrink.
  Actions respect ``PERITEXT_ELASTIC_COOLDOWN``; the loop thread ticks
  every ``PERITEXT_ELASTIC_INTERVAL`` seconds.  ``PERITEXT_ELASTIC=1``
  attaches a controller to every new ShardedServePlane.

Telemetry: ``elastic.*`` counters (ticks, migrations, failures,
rollbacks, splits, merges, parked deliveries), an ``elastic.migrate``
flow lane per protocol run (terminal outcome ``migrated`` /
``rolled_back``), and an ``elastic`` block in ``obs.status()`` (per-shard
load, last rebalance action, migrations in flight, rollbacks) rendered by
``scripts/ops_top.py``.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from peritext_tpu.runtime import checkpoint, faults, slo, telemetry

_log = logging.getLogger(__name__)


class MigrationError(RuntimeError):
    """A migration failed and was rolled back; the source shard is
    authoritative and the session kept serving there."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# -- the migration protocol ----------------------------------------------------


def migrate_session(plane: Any, name: str, target_shard: int) -> None:
    """Move session ``name`` to ``target_shard`` live (module docstring).

    Raises :class:`MigrationError` after rolling back on any protocol
    failure; raises ``ValueError``/``KeyError`` for caller mistakes
    (unknown session, out-of-range or same-shard target, concurrent
    migration of the same session) before anything is touched.
    """
    with plane._lock:
        sess = plane._sessions.get(name)
        if sess is None:
            raise KeyError(f"unknown session {name!r}")
        if not (0 <= target_shard < len(plane.shards)):
            raise ValueError(
                f"shard {target_shard} out of range [0, {len(plane.shards)})"
            )
        if target_shard == sess.shard:
            raise ValueError(f"session {name!r} is already on shard {target_shard}")
        if sess._parked is not None:
            raise ValueError(f"session {name!r} is already migrating")
        if getattr(sess, "_cold", False):
            # Migration-vs-eviction serialization (runtime/lifecycle.py):
            # a cold session has no device row to move — the caller must
            # hydrate first.  Both protocols park under the facade lock,
            # so a mid-eviction session surfaces as "already migrating".
            raise ValueError(
                f"session {name!r} is evicted (cold); hydrate before migrating"
            )
        source_index = sess.shard
        source_slot = plane.shards[source_index]
        target_slot = plane.shards[target_shard]
        old_inner = sess._inner
        # Park: from here every delivery (client submit, fan-out,
        # anti-entropy) buffers until commit/rollback replays it.
        sess._parked = []
    if telemetry.enabled:
        ctx = telemetry.flow(
            "elastic.migrate", session=name, source=source_index,
            target=target_shard,
        )
        telemetry.counter("elastic.migrations_started")
    else:
        ctx = None
    source_plane = source_slot.plane
    provisioned = False
    try:
        with telemetry.span(
            "elastic.migrate", session=name, source=source_index,
            target=target_shard,
        ):
            telemetry.flow_point(ctx)
            # Step 1: drain the source lane — the parked flag stops new
            # admissions, so after this the lane holds only causally-
            # undeliverable leftovers (swept at commit).
            faults.fire("shard_migrate")
            if source_plane._thread is not None:
                source_plane.flush_and_wait()
            else:
                source_plane.drain()
            # Step 2: export the replica row under the source plane's
            # quiescence barrier (no cohort may be mid-launch over it).
            faults.fire("shard_migrate")
            payload = source_plane.run_quiesced(
                lambda: checkpoint.export_replica(
                    source_slot.universe, sess.replica
                )
            )
            # Step 3: provision the target row (pad consume / pow2 growth /
            # first-session universe bring-up — serve_shard owns the policy).
            faults.fire("shard_migrate")
            with plane._lock:
                plane._provision_locked(target_slot, sess.replica)
                provisioned = True
            # Step 4: import (digest-verified, masked intern remap).
            faults.fire("shard_migrate")
            with plane._lock:
                target_slot.plane.run_quiesced(
                    lambda: checkpoint.import_replica(
                        target_slot.universe, sess.replica, payload
                    )
                )
            # Step 5: the commit gate — the last point a failure can
            # abort; past it the target row is authoritative.
            faults.fire("shard_migrate")
    except BaseException as exc:
        with telemetry.span(
            "elastic.rollback", session=name, source=source_index,
            target=target_shard, error=type(exc).__name__,
        ):
            _rollback(plane, sess, old_inner, target_slot, provisioned, name, exc)
            telemetry.flow_point(ctx, terminal=True, outcome="rolled_back")
        raise MigrationError(
            f"migration of session {name!r} shard {source_index} -> "
            f"{target_shard} failed and rolled back: {exc}"
        ) from exc

    # COMMIT: pure host bookkeeping from here — no fault chokepoints, so
    # the protocol can never die half-moved.
    with plane._lock:
        leftovers = source_plane.evict_session(name)
        plane._evacuate_locked(source_slot, sess.replica)
        new_inner = target_slot.plane.session(
            name,
            sess.replica,
            weight=old_inner.weight,
            priority=old_inner.priority,
            bound=old_inner.bound,
            policy=old_inner.policy,
            block_timeout=old_inner.block_timeout,
        )
        # The per-session patch stream must concatenate seamlessly across
        # the move: hand the target lane the SAME list object.
        new_inner.patch_log = old_inner.patch_log
        sess._inner = new_inner
        sess.shard = target_shard
        if leftovers:
            # Causally-undeliverable submissions swept from the drained
            # source lane: graft the SAME Submission objects into the new
            # lane so the callers' futures still resolve.
            with target_slot.plane._work:
                for sub in leftovers:
                    sub.session = new_inner
                    new_inner._lane.append(sub)
                    new_inner._pending += len(sub.changes)
                target_slot.plane._work.notify_all()
        # Parked deliveries replay FIRST so a parked client submit's
        # future resolves with its own patches; the log-tail catch-up
        # below then re-offers anything it duplicated and the admission
        # gate drops it.
        _replay_parked(sess, new_inner, name, filter_chaos=True)
        # Doc-group log-tail handoff: anything siblings recorded while the
        # session was mid-flight redelivers through the normal gate.
        if sess.doc is not None:
            group = plane._docs.get(sess.doc)
            if group is not None:
                clock = target_slot.plane.run_quiesced(
                    lambda: target_slot.universe.clock(sess.replica)
                )
                missing = group["log"].contiguous(clock)
                if missing:
                    new_inner.submit(missing)
    with telemetry.span(
        "elastic.commit", session=name, source=source_index,
        target=target_shard,
    ):
        if telemetry.enabled:
            telemetry.counter("elastic.migrations")
            telemetry.record(
                "elastic.migrate", outcome="migrated", session=name,
                source=source_index, target=target_shard,
            )
        telemetry.flow_point(ctx, terminal=True, outcome="migrated")


def _replay_parked(sess: Any, inner: Any, name: str, filter_chaos: bool) -> None:
    """Drain the park buffer onto ``inner`` in admission order, binding
    each parked submit's wrapper to its real submission.  On the commit
    path the replayed changes pass the ``shard_migrate`` chaos filter
    (drop/dup/reorder — transport loss across the handoff; anti-entropy
    redelivers doc-grouped drops); the rollback path replays verbatim."""
    buf, sess._parked = sess._parked, None
    for changes, wrapper in buf or []:
        if filter_chaos:
            changes = faults.filter_stream("shard_migrate", changes, stream=name)
        try:
            sub = inner.submit(changes)
        except Exception as exc:
            if wrapper is not None:
                wrapper._reject(exc)
            else:
                _log.warning(
                    "parked delivery replay for %s failed; anti-entropy "
                    "will redeliver", name, exc_info=True,
                )
            continue
        if wrapper is not None:
            wrapper._bind(sub)
    if buf and telemetry.enabled:
        telemetry.counter("elastic.replayed_deliveries", len(buf))


def _rollback(
    plane: Any,
    sess: Any,
    old_inner: Any,
    target_slot: Any,
    provisioned: bool,
    name: str,
    exc: BaseException,
) -> None:
    """Unwind a failed migration: the target row unprovisions, parked
    deliveries replay onto the (still-registered) source lane, and a
    rate-limited black-box dump records the failure."""
    with plane._lock:
        if provisioned:
            try:
                plane._unprovision_locked(target_slot, sess.replica)
            except Exception:
                _log.warning(
                    "rollback of session %s could not unprovision the "
                    "target row; shard %d carries a stray row",
                    name, target_slot.index, exc_info=True,
                )
        _replay_parked(sess, old_inner, name, filter_chaos=False)
    if telemetry.enabled:
        telemetry.counter("elastic.migration_failures")
        telemetry.counter("elastic.rollbacks")
        telemetry.record(
            "elastic.migrate", outcome="rolled_back", session=name,
            error=type(exc).__name__,
        )
    telemetry.blackbox_dump(
        "shard_migrate_failed",
        dedupe_key=f"shard_migrate:{name}",
        session=name,
        target=target_slot.index,
        error=f"{type(exc).__name__}: {exc}",
    )


# -- the autoscaler control loop -----------------------------------------------


class ElasticController:
    """The control loop over one ShardedServePlane (module docstring).

    ``tick()`` makes at most one rebalance decision; threaded mode calls
    it every ``interval`` seconds.  Decisions are pure functions of the
    observed loads + SLO burn state, so a manual-mode test drives the
    loop deterministically."""

    def __init__(
        self,
        plane: Any,
        *,
        interval: Optional[float] = None,
        spread: Optional[float] = None,
        cooldown: Optional[float] = None,
        merge_low: Optional[float] = None,
        watch_slo: bool = True,
        start: bool = True,
    ) -> None:
        self.plane = plane
        # ``watch_slo=False`` blinds the controller to live SLO burn, so
        # decisions become a pure function of the observed loads — what a
        # measurement harness needs for a shape-deterministic warmup
        # (burn depends on real latencies, so a burn-fed decision sequence
        # can mint jit shapes the warmup pass never saw).
        self.watch_slo = watch_slo
        self.interval = (
            interval if interval is not None
            else _env_float("PERITEXT_ELASTIC_INTERVAL", 1.0)
        )
        # A hot shard must carry ``spread`` times the coldest shard's
        # load (+1 smooths the empty-shard asymptote) before a migration
        # is worth its protocol cost.
        self.spread = (
            spread if spread is not None
            else _env_float("PERITEXT_ELASTIC_SPREAD", 4.0)
        )
        self.cooldown = (
            cooldown if cooldown is not None
            else _env_float("PERITEXT_ELASTIC_COOLDOWN", 5.0)
        )
        # Fleet-wide pending below this consolidates stragglers (merge).
        self.merge_low = (
            merge_low if merge_low is not None
            else _env_float("PERITEXT_ELASTIC_MERGE_LOW", 1.0)
        )
        # Consecutive quiet ticks required before a merge: a migration's
        # own source-shard drain momentarily empties the lanes, and
        # without this hysteresis a split's very next tick would read
        # that lull as "quiet fleet" and merge the session straight back.
        self.merge_quiet = max(
            1, int(_env_float("PERITEXT_ELASTIC_MERGE_QUIET", 3.0))
        )
        self._quiet_ticks = 0
        self.stats: Dict[str, int] = {
            "ticks": 0,
            "migrations": 0,
            "splits": 0,
            "merges": 0,
            "failures": 0,
            "rollbacks": 0,
        }
        self.last_action: Optional[Dict[str, Any]] = None
        self._last_action_t = float("-inf")
        self._inflight = 0
        self._closed = False
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        telemetry.register_status_source("elastic", self._status)
        if start:
            self.start()

    # -- observation ---------------------------------------------------------

    def _loads(self) -> List[Dict[str, Any]]:
        """Per-shard load snapshot (facade lock): pending changes +
        session count, the metric placement and the autoscaler share."""
        plane = self.plane
        with plane._lock:
            out = []
            for s in plane.shards:
                load = plane._shard_load_locked(s)
                out.append(
                    {
                        "shard": s.index,
                        "load": load,
                        # Traffic pressure alone (load minus the session
                        # count): the merge path judges quietness on this,
                        # because sessions never drain away on their own.
                        "pending": load - len(s.real),
                        "sessions": len(s.real),
                        "width": (
                            len(s.universe.replica_ids)
                            if s.universe is not None else 0
                        ),
                    }
                )
            return out

    def _burning(self) -> bool:
        if not self.watch_slo:
            return False
        plan = slo.active()
        return plan is not None and plan.breach_active()

    def _status(self) -> Dict[str, Any]:
        return {
            "plane": self.plane.name,
            "interval": self.interval,
            "spread": self.spread,
            "cooldown": self.cooldown,
            "loads": self._loads(),
            "slo_burning": self._burning(),
            "last_action": self.last_action,
            "in_flight": self._inflight,
            "ticks": self.stats["ticks"],
            "migrations": self.stats["migrations"],
            "rollbacks": self.stats["rollbacks"],
            "failures": self.stats["failures"],
        }

    # -- the decision --------------------------------------------------------

    def _pick_victim(self, shard_index: int) -> Optional[str]:
        """The hot shard's busiest migratable session (deterministic:
        max pending, ties broken by name)."""
        plane = self.plane
        with plane._lock:
            candidates = [
                s for s in plane._sessions.values()
                if s.shard == shard_index
                and s._parked is None
                and not getattr(s, "_cold", False)
            ]
            if not candidates:
                return None
            return max(
                candidates, key=lambda s: (s._inner.pending(), s.name)
            ).name

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control decision.  Returns the action taken ("split" /
        "merge") or None (cooldown, balanced fleet, nothing migratable)."""
        self.stats["ticks"] += 1
        if telemetry.enabled:
            telemetry.counter("elastic.ticks")
        t = time.monotonic() if now is None else now
        if t - self._last_action_t < self.cooldown:
            return None
        loads = self._loads()
        if len(loads) < 2:
            return None
        burning = self._burning()
        hot = max(loads, key=lambda e: (e["pending"], e["sessions"]))
        cold = min(loads, key=lambda e: (e["pending"], e["sessions"], e["shard"]))
        action: Optional[str] = None
        victim: Optional[str] = None
        target: Optional[int] = None
        # Split on traffic pressure (pending spread), never on session
        # count alone — an idle-but-populated fleet must not oscillate.
        # Under an active SLO burn, session imbalance >= 2 also splits
        # (narrower hot-shard width is the latency lever), and moving one
        # session strictly shrinks the imbalance, so burn-driven splits
        # terminate at a balanced fleet.
        spread_hit = hot["pending"] >= self.spread * (cold["pending"] + 1)
        burn_hit = burning and hot["sessions"] >= cold["sessions"] + 2
        quiet = not burning and sum(e["pending"] for e in loads) <= self.merge_low
        self._quiet_ticks = self._quiet_ticks + 1 if quiet else 0
        if (
            hot["sessions"] >= 2
            and hot["shard"] != cold["shard"]
            and (spread_hit or burn_hit)
        ):
            # Split: shed the hottest shard's busiest session to the
            # coldest shard.
            action, target = "split", cold["shard"]
            victim = self._pick_victim(hot["shard"])
            self._quiet_ticks = 0
        elif quiet and self._quiet_ticks >= self.merge_quiet:
            # Merge: a quiet fleet consolidates a straggler session into a
            # shard with free pad room, so the donor shard's width (and
            # its compiled-program footprint) can shrink.  The host always
            # carries at least as many sessions as the donor, so
            # consolidation is monotone — no swap loops.
            donors = [e for e in loads if 0 < e["sessions"]]
            if len(donors) >= 2:
                donor = min(donors, key=lambda e: (e["sessions"], e["shard"]))
                plane = self.plane
                with plane._lock:
                    hosts = [
                        e for e in loads
                        if e["shard"] != donor["shard"]
                        and e["sessions"] >= donor["sessions"]
                        and plane.shards[e["shard"]].pad_ids
                    ]
                if hosts:
                    host = max(hosts, key=lambda e: (e["sessions"], -e["shard"]))
                    action, target = "merge", host["shard"]
                    victim = self._pick_victim(donor["shard"])
        if action is None or victim is None or target is None:
            return None
        self._inflight += 1
        try:
            migrate_session(self.plane, victim, target)
        except MigrationError:
            # Every failed migration rolled back exactly once.
            self.stats["failures"] += 1
            self.stats["rollbacks"] += 1
            self._record_action(action, victim, target, t, ok=False)
            return None
        except (KeyError, ValueError):
            # The fleet changed under the decision (session closed or
            # moved concurrently); not a protocol failure.
            return None
        finally:
            self._inflight -= 1
        self.stats["migrations"] += 1
        self.stats["splits" if action == "split" else "merges"] += 1
        self._record_action(action, victim, target, t, ok=True)
        return action

    def _record_action(
        self, action: str, victim: str, target: int, t: float, ok: bool
    ) -> None:
        self._last_action_t = t
        self.last_action = {
            "action": action,
            "session": victim,
            "to_shard": target,
            "ok": ok,
            "t": time.time(),
        }

    # -- the loop thread -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self._closed:
            return
        self._thread = threading.Thread(
            target=self._run,
            name=f"peritext-{self.plane.name}-elastic",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._closed:
            self._wake.wait(self.interval)
            if self._closed:
                return
            try:
                self.tick()
            except Exception:
                _log.warning(
                    "elastic tick failed; the loop survives", exc_info=True
                )

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
