"""Checkpoint/resume: device-state snapshots + change-log tail replay.

The reference's durability story is the append-only per-actor change log —
any replica is reconstructible by replaying logs through applyChange (that
is exactly how its failure-trace JSONs work, SURVEY.md §5).  This module
keeps that model and adds the TPU-scale fast path: snapshot the dense device
state (one npz of the stacked arrays + a JSON control-plane sidecar), then on
resume replay only the log tail past the snapshot's vector clocks.

Format:
- ``<path>.npz``  — every DocState leaf, batched [R, ...]
- ``<path>.json`` — replica ids, per-replica clocks/lengths/mark counts,
  actor and attr intern tables, capacities, host object stores + device
  text-list bindings
"""
from __future__ import annotations

import hashlib
import io
import json
import logging
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from peritext_tpu import schema
from peritext_tpu.ids import ActorRegistry
from peritext_tpu.ops.encode import AttrRegistry
from peritext_tpu.ops.state import DocState
from peritext_tpu.ops.universe import TpuUniverse
from peritext_tpu.oracle.doc import ObjectStore
from peritext_tpu.runtime import faults
from peritext_tpu.runtime import telemetry

import dataclasses

_log = logging.getLogger(__name__)

_STATE_FIELDS = [f.name for f in dataclasses.fields(DocState)]

# Sidecar format version.  2 = the host-object-store plane ('stores' +
# 'text_objs' + 'format'); 1 (implicit, no 'format' key) = the same layout
# before the version field existed.  Anything older (the pre-round-2
# 'roots' layout) is rejected with an explicit error instead of a bare
# KeyError deep in load.
CHECKPOINT_FORMAT = 2


def save_universe(uni: TpuUniverse, path: str) -> None:
    with telemetry.span("checkpoint.save", path=path):
        _save_universe(uni, path)
    if telemetry.enabled:
        telemetry.counter("checkpoint.saves")


def _save_universe(uni: TpuUniverse, path: str) -> None:
    # Chaos chokepoint: an injected failure raises before anything is
    # written; the previous generation stays intact (atomic writes below).
    faults.fire("checkpoint_write")
    arrays = {f: np.asarray(getattr(uni.states, f)) for f in _STATE_FIELDS}
    # Write both files atomically so a crash mid-save never destroys the
    # previous good snapshot.  The npz payload is built in memory first so
    # its digest can ride in the sidecar — restore verifies it and treats a
    # mismatch (truncation, bit rot) like any other unreadable generation.
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    payload = buf.getvalue()
    tmp_npz = path + ".npz.tmp"
    with open(tmp_npz, "wb") as f:
        f.write(payload)
    os.replace(tmp_npz, path + ".npz")
    sidecar = {
        "format": CHECKPOINT_FORMAT,
        "npz_sha256": hashlib.sha256(payload).hexdigest(),
        "replica_ids": uni.replica_ids,
        "clocks": uni.clocks,
        "lengths": uni.lengths,
        "mark_counts": uni.mark_counts,
        "stores": [s.to_json() for s in uni.stores],
        "text_objs": uni.text_objs,
        "capacity": uni.capacity,
        "max_mark_ops": uni.max_mark_ops,
        "max_actors": uni.max_actors,
        "actors": uni.actors.actors,
        "attrs": uni.attrs.values,
        # Snapshots index mark types by position in the runtime-extensible
        # schema registry; persist the registry so a restoring process with
        # different register_mark_type calls can't silently remap types.
        "mark_schema": [
            {
                "name": name,
                "inclusive": spec.inclusive,
                "allow_multiple": spec.allow_multiple,
                "attr_keys": list(spec.attr_keys),
                "excludes": spec.excludes,
            }
            for name, spec in schema.MARK_SPEC.items()
        ],
    }
    tmp = path + ".json.tmp"
    with open(tmp, "w") as f:
        json.dump(sidecar, f)
    os.replace(tmp, path + ".json")
    # Crash-corruption drill (``checkpoint_write:corrupt=N``): truncate the
    # just-written npz after the atomic replace, simulating a torn write
    # that slipped past rename atomicity (e.g. lost page cache on power
    # failure).  restore_latest must detect it via the digest and fall back.
    if faults.take("checkpoint_write", "corrupt"):
        with open(path + ".npz", "r+b") as f:
            f.truncate(max(1, len(payload) // 2))


def _restore_mark_schema(sidecar: Dict[str, Any]) -> None:
    """Validate the snapshot's mark registry against the live one.

    Stored mark-type ids are positional, so the snapshot's registry must be
    a prefix of the current one (same names, same flags, same order).
    Types the snapshot has beyond the live registry are auto-registered;
    any mismatch within the shared prefix fails loudly.
    """
    saved = sidecar.get("mark_schema")
    if saved is None:  # pre-schema-sidecar snapshot: assume the core four
        return
    live = list(schema.ALL_MARKS)
    for i, entry in enumerate(saved):
        if i < len(live):
            name = live[i]
            spec = schema.MARK_SPEC[name]
            if (
                entry["name"] != name
                or entry["inclusive"] != spec.inclusive
                or entry["allow_multiple"] != spec.allow_multiple
                or tuple(entry["attr_keys"]) != spec.attr_keys
                # Older snapshots (no 'excludes' key) validate flags only.
                or ("excludes" in entry and entry["excludes"] != spec.excludes)
            ):
                raise ValueError(
                    f"snapshot mark schema mismatch at id {i}: snapshot has "
                    f"{entry['name']!r}, process has {name!r} (or flags differ); "
                    "register mark types in the same order before restoring"
                )
        else:
            schema.register_mark_type(
                entry["name"],
                inclusive=entry["inclusive"],
                allow_multiple=entry["allow_multiple"],
                attr_keys=tuple(entry["attr_keys"]),
                excludes=entry.get("excludes"),
            )


def load_universe(path: str) -> TpuUniverse:
    with telemetry.span("checkpoint.restore", path=path):
        uni = _load_universe(path)
    if telemetry.enabled:
        telemetry.counter("checkpoint.restores")
    return uni


def _load_universe(path: str) -> TpuUniverse:
    with open(path + ".json") as f:
        sidecar = json.load(f)
    fmt = sidecar.get("format", 1)
    if fmt > CHECKPOINT_FORMAT or "stores" not in sidecar:
        raise ValueError(
            f"snapshot {path!r} has format {fmt} "
            f"(this build reads <= {CHECKPOINT_FORMAT}"
            + ("" if "stores" in sidecar else "; pre-round-2 'roots' layout")
            + "); re-save it with a matching build or replay its change log"
        )
    _restore_mark_schema(sidecar)
    uni = TpuUniverse(
        sidecar["replica_ids"],
        capacity=sidecar["capacity"],
        max_mark_ops=sidecar["max_mark_ops"],
        max_actors=sidecar["max_actors"],
    )
    uni.clocks = [dict(c) for c in sidecar["clocks"]]
    uni.lengths = list(sidecar["lengths"])
    uni.mark_counts = list(sidecar["mark_counts"])
    uni.text_objs = list(sidecar["text_objs"])
    # Reconstruct store-version classes from content so a restored converged
    # fleet keeps the one-copy-per-class host plane (universe.store_versions
    # invariant: equal version ⟹ equal store): deserialize ONE store per
    # distinct digest and share the instance across its class — restore is
    # O(classes), not O(R), in both time and memory.
    digest_version: Dict[str, int] = {}
    digest_store: Dict[str, ObjectStore] = {}
    versions, stores = [], []
    for s in sidecar["stores"]:
        d = json.dumps(s, sort_keys=True)
        if d not in digest_version:
            uni._store_version_counter += 1
            digest_version[d] = uni._store_version_counter
            digest_store[d] = ObjectStore.from_json(s)
        versions.append(digest_version[d])
        stores.append(digest_store[d])
    uni.stores = stores
    uni.store_versions = versions
    actors = ActorRegistry()
    for actor in sidecar["actors"]:
        actors.intern(actor)
    uni.actors = actors
    attrs = AttrRegistry()
    for attr in sidecar["attrs"]:
        attrs.intern(attr)
    uni.attrs = attrs

    with open(path + ".npz", "rb") as f:
        payload = f.read()
    expected = sidecar.get("npz_sha256")
    if expected is not None and hashlib.sha256(payload).hexdigest() != expected:
        raise ValueError(
            f"snapshot {path!r}: state payload digest mismatch "
            "(truncated or corrupt .npz)"
        )
    data = np.load(io.BytesIO(payload))
    uni.states = DocState(**{f: jax.numpy.asarray(data[f]) for f in _STATE_FIELDS})
    # Rebuild the allowMultiple group census (gates the cached patch scan)
    # from the restored mark tables.
    from peritext_tpu.ops.universe import fold_multi_groups

    for r in range(len(uni.replica_ids)):
        count = uni.mark_counts[r]
        fold_multi_groups(
            uni._multi_groups,
            types=data["mark_type"][r][:count],
            attr_ids=data["mark_attr"][r][:count],
            ctrs=data["mark_ctr"][r][:count],
            act_ids=data["mark_act"][r][:count],
        )
    return uni


def _row_digest(arrays: Dict[str, np.ndarray]) -> str:
    """Deterministic digest of one replica row's state arrays (field order,
    dtype and shape included, so a torn or re-shaped handoff can't verify)."""
    h = hashlib.sha256()
    for f in _STATE_FIELDS:
        a = np.ascontiguousarray(arrays[f])
        h.update(f"{f}:{a.dtype}:{a.shape};".encode())
        h.update(a.tobytes())
    return h.hexdigest()


def export_replica(uni: TpuUniverse, replica: str) -> Dict[str, Any]:
    """Snapshot ONE replica row as a self-contained in-memory payload.

    The live-migration handoff (runtime/elastic.py): the payload carries the
    row's device state (D2H), its host control planes (clock / length / mark
    count / object store / text binding), and the SOURCE universe's intern
    tables — elem_act / mark_act hold registry-LOCAL actor ids and mark_attr
    holds AttrRegistry-local ids, so :func:`import_replica` must remap them
    into the target's registries.  A digest over the state arrays rides
    along; import verifies it so a torn handoff fails loudly instead of
    corrupting the target fleet.
    """
    with telemetry.span("checkpoint.export_replica", replica=replica):
        i = uni.index_of[replica]
        arrays = {
            f: np.asarray(np.asarray(getattr(uni.states, f))[i])
            for f in _STATE_FIELDS
        }
        payload = {
            "replica": replica,
            "arrays": arrays,
            "capacity": uni.capacity,
            "max_mark_ops": uni.max_mark_ops,
            "clock": dict(uni.clocks[i]),
            "length": uni.lengths[i],
            "mark_count": uni.mark_counts[i],
            "store": uni.stores[i].to_json(),
            "text_obj": uni.text_objs[i],
            "actors": uni.actors.actors,
            "attrs": uni.attrs.values,
            "digest": _row_digest(arrays),
        }
    if telemetry.enabled:
        telemetry.counter("checkpoint.replica_exports")
    return payload


def import_replica(uni: TpuUniverse, replica: str, payload: Dict[str, Any]) -> None:
    """Graft an exported replica row onto an EMPTY row of another universe.

    The target row must never have ingested anything (empty clock) — the
    migration protocol provisions it via the pow2 pad plane + rename.  Actor
    and attr ids are remapped through the target registries with MASKS
    (elem_act only where ``elem_ctr > 0``, mark rows only below
    ``mark_count``, attrs only where ``>= 0``): inert slots hold 0, which is
    a *valid* intern id, and rewriting them would scramble dead-slot
    contents the kernels rely on being stable.  ``bnd_mask`` needs no remap
    (bits index the same replica's mark-op rows, which move row-for-row).
    Capacities reconcile both ways: the target grows to fit the payload
    (pow2, normal `_ensure_capacity`), a smaller payload row grows to the
    target's buckets.
    """
    with telemetry.span("checkpoint.import_replica", replica=replica):
        _import_replica(uni, replica, payload)
    if telemetry.enabled:
        telemetry.counter("checkpoint.replica_imports")


def _import_replica(uni: TpuUniverse, replica: str, payload: Dict[str, Any]) -> None:
    from peritext_tpu.ops.state import grow_state
    from peritext_tpu.ops.universe import fold_multi_groups

    i = uni.index_of[replica]
    if uni.clocks[i]:
        raise ValueError(
            f"cannot import over non-empty replica {replica!r} "
            f"(clock {uni.clocks[i]}); provision a fresh row first"
        )
    arrays = payload["arrays"]
    if _row_digest(arrays) != payload["digest"]:
        raise ValueError(
            f"replica payload digest mismatch for {replica!r} "
            "(torn or corrupted handoff)"
        )
    # Grow the target's buckets to fit the payload, then the payload row to
    # the target's (possibly already larger) buckets.
    uni._ensure_capacity(payload["capacity"], payload["max_mark_ops"])
    # Masked intern-id remap through the TARGET registries.
    actor_map = np.asarray(
        [uni.actors.intern(a) for a in payload["actors"]], np.int32
    )
    attr_map = np.asarray(
        [uni.attrs.intern(a) for a in payload["attrs"]], np.int32
    )
    elem_act = np.array(arrays["elem_act"], np.int32)
    live = np.asarray(arrays["elem_ctr"]) > 0
    if actor_map.size:
        elem_act[live] = actor_map[elem_act[live]]
    mark_act = np.array(arrays["mark_act"], np.int32)
    mark_attr = np.array(arrays["mark_attr"], np.int32)
    mc = int(payload["mark_count"])
    if mc and actor_map.size:
        mark_act[:mc] = actor_map[mark_act[:mc]]
    has_attr = np.zeros(mark_attr.shape, bool)
    has_attr[:mc] = mark_attr[:mc] >= 0
    if attr_map.size:
        mark_attr[has_attr] = attr_map[mark_attr[has_attr]]
    remapped = dict(arrays)
    remapped["elem_act"] = elem_act
    remapped["mark_act"] = mark_act
    remapped["mark_attr"] = mark_attr
    row = DocState(**{f: jax.numpy.asarray(remapped[f]) for f in _STATE_FIELDS})
    if row.capacity < uni.capacity or row.max_mark_ops < uni.max_mark_ops:
        row = grow_state(row, uni.capacity, uni.max_mark_ops)
    # One scatter per leaf; assigning ``uni.states`` auto-invalidates the
    # causal mirror (token keyed to the pytree object).
    uni.states = jax.tree.map(
        lambda full, r: full.at[i].set(r), uni.states, row
    )
    uni._wcaches = None  # row contents changed under the winner cache
    uni.clocks[i] = dict(payload["clock"])
    uni.lengths[i] = int(payload["length"])
    uni.mark_counts[i] = int(payload["mark_count"])
    uni.stores[i] = ObjectStore.from_json(payload["store"])
    uni._store_version_counter += 1
    uni.store_versions[i] = uni._store_version_counter
    uni.text_objs[i] = payload["text_obj"]
    # Fold the imported mark rows (REMAPPED ids) into the allowMultiple
    # group census so the cached-patch-scan gate stays conservative.
    fold_multi_groups(
        uni._multi_groups,
        types=np.asarray(arrays["mark_type"])[:mc],
        attr_ids=mark_attr[:mc],
        ctrs=np.asarray(arrays["mark_ctr"])[:mc],
        act_ids=mark_act[:mc],
    )


class CheckpointManager:
    """Rotating snapshot schedule: save every ``interval`` steps, keep the
    newest ``keep`` snapshots, resume from the newest loadable one.

    Snapshots are written atomically (save_universe), so a crash mid-save
    leaves the previous generation intact; ``latest`` is derived from the
    on-disk generation numbers rather than a pointer file.
    """

    def __init__(self, directory: str, interval: int = 1, keep: int = 3) -> None:
        self.directory = directory
        self.interval = max(1, interval)
        self.keep = max(1, keep)
        self._step = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self, generation: int) -> str:
        return os.path.join(self.directory, f"snap-{generation:08d}")

    def generations(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("snap-") and name.endswith(".json"):
                try:
                    out.append(int(name[5:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def maybe_save(self, uni: TpuUniverse) -> Optional[str]:
        """Call once per ingest step; saves on the schedule and prunes."""
        self._step += 1
        if self._step % self.interval != 0:
            return None
        return self.save(uni)

    def save(self, uni: TpuUniverse) -> str:
        gens = self.generations()
        generation = (gens[-1] + 1) if gens else 0
        path = self._path(generation)
        save_universe(uni, path)
        for old in self.generations()[: -self.keep]:
            for suffix in (".json", ".npz"):
                try:
                    os.remove(self._path(old) + suffix)
                except OSError:
                    pass
        return path

    def restore_latest(self, log: Any = None) -> Optional[TpuUniverse]:
        """Newest loadable snapshot (+ optional log-tail replay), or None.

        Only snapshot-load failures fall back a generation; errors during
        log-tail replay indicate a log problem and propagate.
        """
        import zipfile

        for generation in reversed(self.generations()):
            try:
                uni = load_universe(self._path(generation))
            except (OSError, ValueError, KeyError, zipfile.BadZipFile) as exc:
                # Corrupt/partial snapshot (bad digest, truncated zip,
                # unreadable sidecar): log it and fall back a generation —
                # the change log replays the gap, so an older snapshot only
                # costs replay time, never data.
                if telemetry.enabled:
                    telemetry.counter("checkpoint.corrupt_fallbacks")
                    telemetry.record(
                        "checkpoint.restore",
                        outcome="corrupt_fallback",
                        generation=generation,
                        error=type(exc).__name__,
                    )
                # Durability post-mortem: a corrupt generation means a torn
                # write slipped past rename atomicity — worth a black-box
                # dump (no-op unless PERITEXT_BLACKBOX is armed).
                telemetry.blackbox_dump(
                    "checkpoint_corrupt",
                    generation=generation,
                    error=f"{type(exc).__name__}: {exc}",
                )
                _log.warning(
                    "checkpoint generation %d unreadable (%s: %s); "
                    "falling back to the previous generation",
                    generation,
                    type(exc).__name__,
                    exc,
                )
                continue
            if log is not None:
                _replay_tail(uni, log)
            return uni
        return None


def _replay_tail(uni: TpuUniverse, log: Any, replicas: Optional[List[str]] = None) -> None:
    frontier = log.clock()
    batches: Dict[str, List[Dict[str, Any]]] = {}
    for name in replicas or uni.replica_ids:
        batches[name] = log.missing_changes(frontier, uni.clock(name))
    uni.apply_changes(batches)


def resume_universe(
    path: str, log: Any, replicas: Optional[List[str]] = None
) -> TpuUniverse:
    """Load a snapshot and replay the change-log tail past its clocks.

    ``log`` is a :class:`peritext_tpu.runtime.log.ChangeLog` (or anything
    with ``missing_changes``).  Replicas named in the snapshot resume to the
    log's frontier; this is the crash-recovery path.
    """
    uni = load_universe(path)
    _replay_tail(uni, log, replicas)
    return uni
