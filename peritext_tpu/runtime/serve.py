"""Serving plane: multi-session admission + deadline-aware continuous
batching in front of universe ingest.

Until this module existed every caller drove ``Universe.apply_changes*`` /
``TpuDoc.change`` directly: one chatty session's per-keystroke launches
starve everyone else, every odd batch shape is a fresh XLA compile, and
there is no latency contract between "change submitted" and "patches
returned".  Server-assisted collaboration frameworks (Collabs, PAPERS.md)
and fast batched merging (Eg-walker) argue the same point: the win comes
from an explicit serving layer that aggregates many clients into few
well-shaped merge operations — the continuous-batching shape of a
production inference stack, applied to CRDT ingest.

The pieces:

- **Sessions** (:class:`ServeSession`): one per fronted replica.  Clients
  ``submit(changes)`` into the session's admission lane and get a
  :class:`Submission` future back; the future resolves with exactly the
  patches *that submission's* changes emitted.  Per-session backpressure
  reuses the ChangeQueue policy vocabulary (``block`` / ``coalesce`` /
  ``shed`` — runtime/queue.py) over a lane bound.
- **The scheduler** (:class:`ServePlane`): forms cross-session cohorts and
  flushes when either the pow2 batch target (``PERITEXT_SERVE_BATCH``)
  fills or the oldest admitted submission ages past
  ``PERITEXT_SERVE_DEADLINE_MS``.  Fairness is deficit-weighted
  round-robin across sessions (deficits persist across flushes, so a
  100:1 hot session cannot starve a cold one past its next cohort), with
  a strict priority lane: ``interactive`` sessions are served before
  ``bulk`` (anti-entropy backfill) every flush.
- **One launch per cohort**: the flush calls
  ``TpuUniverse.apply_changes_with_patches(..., with_positions=True)`` —
  one causally-gated device launch for every admitted session — and
  splits each replica's positioned patch stream back into exact
  per-submission lists by flat-op-position ranges.  Because replicas are
  independent and per-session admission preserves FIFO, every session's
  concatenated stream is **byte-identical** to ingesting its changes one
  at a time (``sync.causal_order`` semantics; tests/test_serve.py pins
  the differential, including under seeded chaos and the oracle-degrade
  path).
- **Causal gating at admission**: cohort formation classifies each
  submission against a working clock (duplicates drop exactly like the
  universe gate; causally-unready submissions defer in the lane and
  retry next flush — ``serve.deferred``), so one session's gap can never
  fail another session's launch.
- **Health-plane routing**: when the ``device_launch`` breaker is OPEN,
  ``PERITEXT_SERVE_ON_OPEN`` picks the policy — ``degrade`` (default)
  flushes anyway and lets ingest fast-fail into the oracle CPU path at
  degrade-only cost; ``hold`` parks cohorts until the breaker recovers,
  shedding them (``ServeShedError``) once the oldest submission ages past
  the deadline.
- **Observability**: every submission mints/joins a ``serve.submit``
  causal lane (admission → flush → launch/readback/assembly → resolve
  renders arrow-linked in Perfetto), resolution feeds the
  ``e2e.admit_to_applied`` histogram, ``serve.*`` counters ride into
  ``obs.summary()`` (and therefore bench JSON stamps and the fuzz
  ``--chaos`` footer), deadline-miss streaks and shed events fire
  black-box dumps, and the ``serve_admit`` fault site joins the chaos
  grammar (fail/wedge hit submit; drop/dup/reorder filter the submitted
  changes).
- **Shape bucketing**: the batch target is pow2 and the underlying encode
  paths pad rows to pow2 buckets, so steady-state cohorts reuse a handful
  of compiled programs; the plane tracks the (replicas, capacity,
  ops-bucket, marks-bucket) shape key per flush as
  ``serve.compile_cache_{hit,miss}``.

Disabled-telemetry contract: every serve site guards on the single
``telemetry.enabled`` attribute (one attr check, no call, no allocation —
tests/test_telemetry.py pins it), and a telemetry-on serving run is
byte-identical to off.

Threading: ``ServePlane(..., start=True)`` runs the scheduler on a daemon
thread (submissions may ``wait=True`` / ``Submission.result()``).
``start=False`` is manual mode — tests, the fuzzer and A/B harnesses call
``step()`` / ``drain()`` on their own thread for deterministic schedules.
The plane assumes it owns its universe's ingest (interleaving direct
``apply_changes*`` calls between flushes is allowed; concurrent ones are
not).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from peritext_tpu.runtime import faults, health, telemetry
from peritext_tpu.runtime.queue import POLICIES, QueueFullError
from peritext_tpu.runtime.sync import causal_order

Change = Dict[str, Any]
Patch = Dict[str, Any]

_log = logging.getLogger(__name__)

INTERACTIVE = "interactive"
BULK = "bulk"
_PRIORITIES = (INTERACTIVE, BULK)

ON_OPEN_DEGRADE = "degrade"
ON_OPEN_HOLD = "hold"
_ON_OPEN = (ON_OPEN_DEGRADE, ON_OPEN_HOLD)

# Consecutive deadline misses that constitute a storm worth a post-mortem.
_MISS_STORM = 8


class ServeShedError(RuntimeError):
    """A submission was shed before it could be applied (lane backpressure
    under the ``shed`` policy, or the hold-until-deadline breaker policy
    giving up on a sick backend)."""


class ServeClosedError(RuntimeError):
    """The serving plane was closed with this submission still pending."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")


def _bucket_pow2(n: int) -> int:
    out = 1
    while out < n:
        out *= 2
    return out


def cohort_shape_key(universe: Any, per_replica: Dict[str, List[Change]]) -> tuple:
    """The jit-cache shape proxy for one cohort: replica count and device
    capacity plus pow2 buckets of the widest per-replica op and mark-row
    counts — the axes that dominate the compiled program shape (encode
    pads rows to these buckets).  THE one definition, shared by the
    plane's ``serve.compile_cache_{hit,miss}`` tracking and the serve A/B
    harness's naive-leg shape count, so the two sides always compare the
    same key."""
    max_ops = 0
    max_marks = 0
    for stream in per_replica.values():
        ops = sum(len(c["ops"]) for c in stream)
        marks = sum(
            1
            for c in stream
            for op in c["ops"]
            if op.get("action") in ("addMark", "removeMark")
        )
        max_ops = max(max_ops, ops)
        max_marks = max(max_marks, marks)
    return (
        len(universe.replica_ids),
        universe.capacity,
        _bucket_pow2(max(1, max_ops)),
        _bucket_pow2(max(1, max_marks)),
    )


def _classify(
    changes: Sequence[Change], clock: Dict[str, int]
) -> Tuple[Optional[List[Change]], Optional[Dict[str, int]]]:
    """Dispatchability of one submission against a working clock.

    Mirrors the universe gate exactly (ops/universe.py ``_gate``):
    already-seen seqs drop as duplicates, then :func:`causal_order`
    arranges the fresh remainder in the delivery-order-preserving causal
    order the launch will use.  Returns ``(ordered_fresh, advanced_clock)``
    when the whole submission is dispatchable, or ``(None, None)`` when
    any fresh change's dependencies are unsatisfiable from this clock (the
    whole submission defers in the lane — splitting it would tear the
    session's stream).  Because each admitted submission's ordered changes
    are sequentially ready from the working clock, the flush's
    concatenated per-replica stream passes the universe gate unchanged —
    which is what makes the per-submission flat-op position ranges exact.
    ``clock`` is never mutated.
    """
    seen = set()
    fresh: List[Change] = []
    for c in changes:
        key = (c["actor"], c["seq"])
        if c["seq"] > clock.get(c["actor"], 0) and key not in seen:
            seen.add(key)
            fresh.append(c)
    if not fresh:
        return [], clock
    try:
        ordered = causal_order(fresh, clock)
    except ValueError:
        return None, None
    advanced = dict(clock)
    for c in ordered:
        advanced[c["actor"]] = c["seq"]
    return ordered, advanced


class Submission:
    """One ``submit()`` call's future.  Resolves with exactly the patches
    this submission's changes emitted (in stream order), or raises the
    admission/flush error.  Under the ``coalesce`` policy a submit at the
    bound may return the lane-tail submission instead of a fresh one —
    the merged changes then resolve jointly through the shared handle."""

    __slots__ = (
        "session",
        "changes",
        "ctx",
        "t0",
        "t_done",
        "fresh",
        "flush_seq",
        "lat_class",
        "_range",
        "_event",
        "_patches",
        "_error",
    )

    def __init__(self, session: "ServeSession", changes: List[Change], ctx: Any):
        self.session = session
        self.changes = changes
        self.ctx = ctx
        self.t0 = time.perf_counter()
        self.t_done: Optional[float] = None  # perf_counter at resolution
        # Warm/cold admission class (runtime/lifecycle.py): "cold" when this
        # submission's admission had to hydrate an evicted doc first, "warm"
        # for lifecycle-managed resident admissions, None otherwise.  Feeds
        # the e2e.admit_to_applied_{warm,cold} split histograms so cold-start
        # SLOs are first-class PERITEXT_SLO objectives.
        self.lat_class: Optional[str] = None
        self.fresh: Optional[List[Change]] = None
        self.flush_seq: Optional[int] = None
        self._range: Tuple[int, int] = (0, 0)
        self._event = threading.Event()
        self._patches: Optional[List[Patch]] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[Patch]:
        """Block until applied; returns this submission's patches (raises
        the admission/flush error instead when it failed)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"submission to session {self.session.name!r} still pending "
                f"after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._patches if self._patches is not None else []

    def _resolve(self, patches: List[Patch]) -> None:
        self._patches = patches
        self.t_done = time.perf_counter()
        self._event.set()

    def _reject(self, error: BaseException) -> None:
        self._error = error
        self.t_done = time.perf_counter()
        self._event.set()


class ServeSession:
    """One client session's admission lane, fronting exactly one universe
    replica.  Construct via :meth:`ServePlane.session`."""

    def __init__(
        self,
        plane: "ServePlane",
        name: str,
        replica: str,
        weight: int,
        priority: str,
        bound: int,
        policy: str,
        block_timeout: Optional[float],
        record_stream: bool,
    ) -> None:
        if priority not in _PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r}; known: {', '.join(_PRIORITIES)}"
            )
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; known policies: {', '.join(POLICIES)}"
            )
        if weight < 1:
            raise ValueError(f"weight must be >= 1, got {weight}")
        self._plane = plane
        self.name = name
        self.replica = replica
        self.weight = weight
        self.priority = priority
        self.bound = max(0, bound)
        self.policy = policy
        self.block_timeout = block_timeout
        # The lane: pending submissions, FIFO.  A list, not a deque —
        # cohort formation removes from arbitrary positions (causally
        # unready submissions are skipped in place).
        self._lane: List[Submission] = []
        self._pending = 0  # pending changes across the lane
        self._deficit = 0.0  # DWRR credit, persists across flushes
        # Optional per-session patch log (admission order): the fuzzer and
        # the differential tests accumulate it; off by default so long-
        # lived sessions don't grow without bound.
        self.patch_log: Optional[List[Patch]] = [] if record_stream else None

    def submit(
        self,
        changes: Sequence[Change],
        wait: bool = False,
        timeout: Optional[float] = None,
    ):
        """Admit a batch of changes.  Returns the :class:`Submission`
        future (or, with ``wait=True``, blocks and returns the patches)."""
        return self._plane._submit(self, list(changes), wait, timeout)

    def pending(self) -> int:
        """Pending (admitted, not yet applied) changes in this lane."""
        with self._plane._lock:
            return self._pending


class ServePlane:
    """The serving plane over one :class:`TpuUniverse` (see the module
    docstring).  ``batch_target`` is pow2-bucketed; ``deadline_ms`` is the
    age of the oldest pending submission that forces a flush."""

    def __init__(
        self,
        universe: Any,
        *,
        batch_target: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        quantum: Optional[int] = None,
        on_open: Optional[str] = None,
        start: bool = True,
        name: str = "serve",
        shard: Optional[int] = None,
    ) -> None:
        self._uni = universe
        self.name = name
        # Shard id when this plane is one slice of a ShardedServePlane
        # (runtime/serve_shard.py): stamps the serve.submit causal lanes
        # and the serve.flush spans, and keys the per-shard
        # serve.shard.<i>.compile_cache_* counters so the shape-bucketing
        # win is attributable per shard (the plane-global aggregate stays).
        self.shard = shard
        self._batch_target = _bucket_pow2(
            max(1, batch_target if batch_target is not None
                else _env_int("PERITEXT_SERVE_BATCH", 64))
        )
        self._deadline_s = (
            deadline_ms if deadline_ms is not None
            else _env_float("PERITEXT_SERVE_DEADLINE_MS", 25.0)
        ) / 1000.0
        self._quantum = max(
            1, quantum if quantum is not None else _env_int("PERITEXT_SERVE_QUANTUM", 8)
        )
        on_open = on_open or os.environ.get("PERITEXT_SERVE_ON_OPEN", ON_OPEN_DEGRADE)
        if on_open not in _ON_OPEN:
            raise ValueError(
                f"unknown on_open policy {on_open!r}; known: {', '.join(_ON_OPEN)}"
            )
        self._on_open = on_open
        self._sessions: Dict[str, ServeSession] = {}
        self._by_replica: Dict[str, ServeSession] = {}
        self._lock = threading.RLock()
        # One condition for all plane state: submitters notify the
        # scheduler, flush completion notifies blocked submitters and
        # drain waiters.
        self._work = threading.Condition(self._lock)
        self._flush_seq = 0
        # True while a formed cohort's launch is in flight OUTSIDE the
        # lock (step() releases _work for the device call).  run_quiesced
        # waits on it: universe mutations (replica add/drop, resharding)
        # must never interleave with a launch that is reading the state.
        self._flush_busy = False
        self._closed = False
        self._drain_req = 0
        self._miss_streak = 0
        self._storm_dumped = False
        self._shapes: set = set()
        # Plane-local mirrors of the serve.* telemetry (available with
        # collection off; the A/B harness and tests read them directly).
        self.stats: Dict[str, int] = {
            "submits": 0,
            "submitted_changes": 0,
            "flushes": 0,
            "flushed_changes": 0,
            "coalesced": 0,
            "shed": 0,
            "deferred": 0,
            "held": 0,
            "deadline_misses": 0,
            "compile_cache_hits": 0,
            "compile_cache_misses": 0,
            "flush_failures": 0,
        }
        self._thread: Optional[threading.Thread] = None
        # Live ops surface (ISSUE 13): the plane contributes per-session
        # lane depth + DWRR deficit to obs.status().  Held as a weakref —
        # a dropped plane silently leaves the surface.
        telemetry.register_status_source("serve", self._status)
        if start:
            self.start()

    def _status(self) -> Dict[str, Any]:
        """This plane's slice of :func:`telemetry.status`: per-session
        admission-lane occupancy (depth in changes, lane entries, DWRR
        deficit, priority/weight) plus the flush/miss/shed tallies an
        operator watches, read under the plane lock."""
        with self._lock:
            sessions = {
                name: {
                    "depth": s._pending,
                    "lane": len(s._lane),
                    "deficit": round(s._deficit, 3),
                    "priority": s.priority,
                    "weight": s.weight,
                }
                for name, s in self._sessions.items()
            }
            out: Dict[str, Any] = {
                "plane": self.name,
                "sessions": sessions,
                "flushes": self.stats["flushes"],
                "deadline_misses": self.stats["deadline_misses"],
                "deferred": self.stats["deferred"],
                "shed": self.stats["shed"],
                "compiled_shapes": len(self._shapes),
                "closed": self._closed,
            }
            if self.shard is not None:
                out["shard"] = self.shard
            return out

    # -- sessions ------------------------------------------------------------

    def session(
        self,
        name: str,
        replica: str,
        *,
        weight: int = 1,
        priority: str = INTERACTIVE,
        bound: Optional[int] = None,
        policy: Optional[str] = None,
        block_timeout: Optional[float] = None,
        record_stream: bool = False,
    ) -> ServeSession:
        """Open a session fronting ``replica`` (must exist in the universe;
        one session per replica — the per-session patch stream IS the
        replica's stream, so two writers would alias it)."""
        if replica not in self._uni.index_of:
            raise KeyError(f"unknown replica {replica!r}")
        with self._lock:
            if name in self._sessions:
                raise ValueError(f"session {name!r} already exists")
            if replica in self._by_replica:
                raise ValueError(
                    f"replica {replica!r} is already fronted by session "
                    f"{self._by_replica[replica].name!r}"
                )
            if bound is None:
                bound = _env_int("PERITEXT_SERVE_BOUND", 0)
            if policy is None:
                policy = os.environ.get("PERITEXT_SERVE_POLICY", "block")
            s = ServeSession(
                self, name, replica, weight, priority, bound, policy,
                block_timeout, record_stream,
            )
            self._sessions[name] = s
            self._by_replica[replica] = s
            if telemetry.enabled:
                telemetry.gauge("serve.sessions", len(self._sessions))
        return s

    def evict_session(self, name: str) -> List[Submission]:
        """Detach a session from this plane (live migration, elastic.py).

        Runs under ``run_quiesced`` semantics on the caller's side: no
        cohort may be forming while a session leaves mid-protocol.  Any
        submissions still in the lane are popped and RETURNED — unresolved,
        so the migration can graft them onto the target plane's lane and
        the callers' futures still resolve with their exact patches.  The
        replica row itself stays in the universe; evacuating it is the
        caller's job."""
        with self._lock:
            s = self._sessions.pop(name, None)
            if s is None:
                raise KeyError(f"unknown session {name!r}")
            self._by_replica.pop(s.replica, None)
            leftover = list(s._lane)
            s._lane = []
            s._pending = 0
            if telemetry.enabled:
                telemetry.gauge("serve.sessions", len(self._sessions))
        return leftover

    # -- admission -----------------------------------------------------------

    def _submit(
        self,
        session: ServeSession,
        changes: List[Change],
        wait: bool,
        timeout: Optional[float],
    ):
        if self._closed:
            raise ServeClosedError(f"serving plane {self.name!r} is closed")
        # Chaos plane: fail/wedge the admission itself, then drop/dup/
        # reorder the submitted changes (client->server transport loss).
        faults.fire("serve_admit")
        changes = faults.filter_stream("serve_admit", changes, stream=session.name)
        if telemetry.enabled:
            flow_meta: Dict[str, Any] = {
                "session": session.name, "changes": len(changes),
            }
            if self.shard is not None:
                flow_meta["shard"] = self.shard
            ctx = telemetry.flow("serve.submit", **flow_meta)
        else:
            ctx = None
        sub = Submission(session, changes, ctx)
        shed: List[Submission] = []
        with telemetry.span("serve.admit", session=session.name, changes=len(changes)):
            telemetry.flow_point(ctx)
            try:
                with self._work:
                    if self._closed:
                        # Re-check under the lock: a close() racing this
                        # submit must not strand the submission in a lane
                        # nothing will ever flush.
                        raise ServeClosedError(
                            f"serving plane {self.name!r} is closed"
                        )
                    sub = self._admit_locked(session, sub, shed)
                    # Mutate the telemetry-off stats mirror under the lock
                    # too — concurrent submitter threads must not lose
                    # increments.
                    self.stats["submits"] += 1
                    self.stats["submitted_changes"] += len(changes)
                    depth = sum(s._pending for s in self._sessions.values())
                    self._work.notify_all()
            except BaseException:
                telemetry.flow_point(ctx, terminal=True, outcome="rejected")
                raise
            if shed:
                # Outside the lock: rejection + the black-box dump do file
                # I/O, which must not stall every other session's submit.
                self._reject_shed(
                    shed, f"lane bound {session.bound} exceeded"
                )
        if telemetry.enabled:
            telemetry.counter("serve.submits")
            telemetry.counter("serve.submitted_changes", len(changes))
            telemetry.gauge_max("serve.depth_max", depth)
        if wait:
            return sub.result(timeout=timeout)
        return sub

    def _admit_locked(
        self, session: ServeSession, sub: Submission, shed_out: List[Submission]
    ) -> Submission:
        n = len(sub.changes)
        if n == 0:
            # An empty submission has nothing to apply: resolve now (the
            # lane must never hold zero-cost entries — DWRR costs are >=1).
            sub._resolve([])
            telemetry.flow_point(sub.ctx, terminal=True, outcome="empty")
            return sub
        bound = session.bound
        if not bound:
            session._lane.append(sub)
            session._pending += n
            return sub
        if session.policy == "block":
            self._admit_blocking_locked(session, n)
            session._lane.append(sub)
            session._pending += n
            return sub
        if session.policy == "coalesce":
            # The bound counts lane ENTRIES (submissions), like the queue's
            # coalesce counts queue entries: at the bound, the new changes
            # merge losslessly into the lane tail and the caller shares the
            # tail's future.
            if len(session._lane) >= bound and session._lane:
                tail = session._lane[-1]
                tail.changes.extend(sub.changes)
                session._pending += n
                self.stats["coalesced"] += n
                if telemetry.enabled:
                    telemetry.counter("serve.coalesced", n)
                telemetry.flow_point(sub.ctx, terminal=True, outcome="coalesced")
                return tail
            session._lane.append(sub)
            session._pending += n
            return sub
        # shed: admit, then drop oldest submissions over the bound.  A
        # single oversized occupant overflows softly (never self-shed the
        # only pending work).  Victims are collected for the caller to
        # reject AFTER the lock releases (the dump does file I/O).
        session._lane.append(sub)
        session._pending += n
        while session._pending > bound and len(session._lane) > 1:
            victim = session._lane.pop(0)
            session._pending -= len(victim.changes)
            shed_out.append(victim)
        return sub

    def _admit_blocking_locked(self, session: ServeSession, n: int) -> None:
        deadline = (
            None
            if session.block_timeout is None
            else time.monotonic() + session.block_timeout
        )
        t0: Optional[float] = None
        while session._pending > 0 and session._pending + n > session.bound:
            if self._closed:
                # close() emptied the lanes and notified: admitting now
                # would strand the submission in a plane nothing flushes.
                raise ServeClosedError(
                    f"serving plane {self.name!r} closed while this submit "
                    "was blocked at the lane bound"
                )
            if t0 is None:
                t0 = time.perf_counter()
                if telemetry.enabled:
                    telemetry.counter("serve.blocked")
            if deadline is None:
                self._work.wait()
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._work.wait(remaining):
                    if telemetry.enabled:
                        telemetry.observe(
                            "serve.block_seconds", time.perf_counter() - t0
                        )
                    raise QueueFullError(
                        f"session {session.name!r} still at bound "
                        f"{session.bound} after {session.block_timeout}s"
                    )
        if self._closed:
            # The wait may have been released BY close() zeroing the lanes.
            raise ServeClosedError(
                f"serving plane {self.name!r} closed while this submit "
                "was blocked at the lane bound"
            )
        if t0 is not None and telemetry.enabled:
            telemetry.observe("serve.block_seconds", time.perf_counter() - t0)

    def _reject_shed(self, shed: List[Submission], why: str) -> None:
        """Resolve shed submissions with ServeShedError + post-mortem.
        Runs OUTSIDE the plane lock (file I/O below); the stats mirror
        mutation re-takes it so concurrent submitters cannot lose the
        increment."""
        total = sum(len(s.changes) for s in shed)
        with self._lock:
            self.stats["shed"] += total
        if telemetry.enabled:
            telemetry.counter("serve.shed", total)
            telemetry.record("serve.shed", outcome="shed", changes=total)
        _log.warning(
            "serving plane %s shed %d change(s) across %d submission(s): %s",
            self.name, total, len(shed), why,
        )
        with telemetry.span("serve.shed", changes=total):
            for sub in shed:
                sub._reject(ServeShedError(
                    f"submission to session {sub.session.name!r} shed: {why}"
                ))
                telemetry.flow_point(sub.ctx, terminal=True, outcome="shed")
        # A shed IS the storm signal: admitted work was dropped on the
        # floor, which only happens when the plane is drowning or the
        # backend is sick past its deadline.
        telemetry.blackbox_dump(
            "serve_shed_storm", plane=self.name, shed=total, why=why
        )

    # -- cohort formation ----------------------------------------------------

    def _depth_oldest_locked(self) -> Tuple[int, float]:
        depth = 0
        oldest = None
        for s in self._sessions.values():
            depth += s._pending
            if s._lane and (oldest is None or s._lane[0].t0 < oldest):
                oldest = s._lane[0].t0
        age = 0.0 if oldest is None else time.perf_counter() - oldest
        return depth, age

    def _form_locked(self) -> Optional[Dict[str, Any]]:
        """Pop one cohort under DWRR + the causal admission gate.

        Per priority class (interactive first), rounds of deficit-weighted
        round-robin: each non-empty lane accrues ``quantum * weight``
        credit per round and spends it on dispatchable submissions in lane
        order (causally-unready ones are skipped in place and retried next
        flush — ``serve.deferred``).  Deficits persist across flushes, so
        heavy lanes pay their debt and a cold session's submission rides
        the very next cohort.  If a full sweep admits nothing while
        dispatchable work exists (an oversized submission), the first
        dispatchable submission force-admits — soft overflow, no
        starvation, no empty-flush spin."""
        ordered_sessions = list(self._sessions.values())
        if not any(s._lane for s in ordered_sessions):
            return None
        budget = self._batch_target
        admitted: List[Submission] = []
        clocks: Dict[str, Dict[str, int]] = {}
        # Per-formation classification cache: an unready submission is
        # re-classified (a causal_order run) only when its replica's
        # working clock has advanced since last time — otherwise repeated
        # DWRR rounds would re-run the gate (and re-count serve.deferred)
        # once per round for the same stuck submission.
        clock_ver: Dict[str, int] = {}
        unready_at: Dict[int, int] = {}
        deferred = 0

        def working_clock(replica: str) -> Dict[str, int]:
            clock = clocks.get(replica)
            if clock is None:
                clock = clocks[replica] = dict(
                    self._uni.clocks[self._uni.index_of[replica]]
                )
            return clock

        def try_take(s: ServeSession, enforce_deficit: bool) -> bool:
            nonlocal budget, deferred
            took = False
            i = 0
            while i < len(s._lane) and budget > 0:
                sub = s._lane[i]
                cost = len(sub.changes)
                if enforce_deficit and s._deficit < cost:
                    break  # out of credit this round; it carries over
                ver = clock_ver.get(s.replica, 0)
                if unready_at.get(id(sub)) == ver:
                    i += 1  # already judged unready at this clock state
                    continue
                fresh, new_clock = _classify(sub.changes, working_clock(s.replica))
                if fresh is None:
                    unready_at[id(sub)] = ver
                    i += 1  # causally unready: stays in lane, retried later
                    deferred += 1
                    continue
                if cost > budget and admitted:
                    break  # doesn't fit this cohort; next flush
                clocks[s.replica] = (
                    dict(new_clock) if new_clock is not None else clocks[s.replica]
                )
                clock_ver[s.replica] = ver + 1
                del s._lane[i]
                s._pending -= cost
                s._deficit = max(0.0, s._deficit - cost)
                budget -= cost
                sub.fresh = fresh
                admitted.append(sub)
                took = True
                if not enforce_deficit:
                    return True  # force-admit exactly one
            return took

        for priority in _PRIORITIES:
            lanes = [s for s in ordered_sessions if s.priority == priority]
            while budget > 0 and any(s._lane for s in lanes):
                progressed = False
                for s in lanes:
                    if budget <= 0:
                        break
                    if not s._lane:
                        s._deficit = 0.0  # idle lanes must not hoard credit
                        continue
                    s._deficit += self._quantum * s.weight
                    if try_take(s, enforce_deficit=True):
                        progressed = True
                if not progressed:
                    break
        if not admitted:
            # Everything pending is either causally deferred or oversized;
            # force-admit one oversized submission so the plane never spins.
            for s in ordered_sessions:
                if s._lane and try_take(s, enforce_deficit=False):
                    break
        if deferred:
            self.stats["deferred"] += deferred
            if telemetry.enabled:
                telemetry.counter("serve.deferred", deferred)
        if not admitted:
            return None
        # Per-replica cohort streams + per-submission flat-op ranges (the
        # positions the universe stamps count ONLY gated-fresh ops, which
        # is exactly what ``fresh`` holds).
        per_replica: Dict[str, List[Change]] = {}
        cursor: Dict[str, int] = {}
        for sub in admitted:
            fresh = sub.fresh or []
            stream = per_replica.setdefault(sub.session.replica, [])
            lo = cursor.get(sub.session.replica, 0)
            hi = lo + sum(len(c["ops"]) for c in fresh)
            sub._range = (lo, hi)
            cursor[sub.session.replica] = hi
            stream.extend(fresh)
        return {"subs": admitted, "per_replica": per_replica}

    # -- flushing ------------------------------------------------------------

    def _flush(self, formed: Dict[str, Any]) -> None:
        subs: List[Submission] = formed["subs"]
        per_replica = formed["per_replica"]
        n_changes = sum(len(s.changes) for s in subs)
        self._flush_seq += 1
        seq = self._flush_seq
        shape = cohort_shape_key(self._uni, per_replica)
        with self._lock:
            # _flush runs outside _work (step released it before the
            # launch); shape_keys()/stats readers on other threads need
            # the mutation fenced.
            hit = shape in self._shapes
            self._shapes.add(shape)
            self.stats["compile_cache_hits" if hit else "compile_cache_misses"] += 1
        if telemetry.enabled:
            suffix = "compile_cache_hit" if hit else "compile_cache_miss"
            telemetry.counter("serve." + suffix)
            if self.shard is not None:
                # Per-shard attribution (keyed, not instead of, the
                # aggregate above): the shape-bucketing claim is judged
                # shard by shard (tests/test_telemetry.py pins both).
                telemetry.counter(f"serve.shard.{self.shard}.{suffix}")
        ctxs = tuple(s.ctx for s in subs if s.ctx is not None)
        err: Optional[BaseException] = None
        out = None
        # Windowed-merge attribution: the cohort window is the union of the
        # member submissions' windows by construction (one gated batch per
        # fronted replica feeds one census), so engagement is read off the
        # universe's stats delta around the launch.
        windowed0 = self._uni.stats.get("windowed_launches", 0)
        t0 = time.perf_counter()
        span_meta: Dict[str, Any] = {
            "flush": seq, "sessions": len(per_replica), "changes": n_changes,
        }
        if self.shard is not None:
            span_meta["shard"] = self.shard
        with telemetry.span("serve.flush", **span_meta):
            for ctx in ctxs:
                telemetry.flow_point(ctx)
            with telemetry.flowing(ctxs):
                try:
                    out = self._uni.apply_changes_with_patches(
                        per_replica, with_positions=True
                    )
                except BaseException as exc:
                    err = exc
            flush_s = time.perf_counter() - t0
            with telemetry.span("serve.resolve", flush=seq):
                if err is None:
                    self._resolve_subs(subs, out, seq, flush_s)
                else:
                    for sub in subs:
                        sub._reject(err)
                        telemetry.flow_point(
                            sub.ctx, terminal=True, outcome="error"
                        )
        if err is not None:
            # The universe's all-or-nothing contract held (nothing
            # committed); the popped submissions carry the error to their
            # callers, who may resubmit.
            self.stats["flush_failures"] += 1
            if telemetry.enabled:
                telemetry.counter("serve.flush_failures")
                telemetry.record(
                    "serve.flush", outcome="error", flush=seq,
                    error=type(err).__name__,
                )
            with self._work:
                self._work.notify_all()
            raise err
        self.stats["flushes"] += 1
        self.stats["flushed_changes"] += n_changes
        flush_windowed = self._uni.stats.get("windowed_launches", 0) > windowed0
        if flush_windowed:
            self.stats["windowed_flushes"] = (
                self.stats.get("windowed_flushes", 0) + 1
            )
        if telemetry.enabled:
            telemetry.counter("serve.flushes")
            telemetry.counter("serve.flushed_changes", n_changes)
            if flush_windowed:
                telemetry.counter("serve.windowed_flushes")
            telemetry.observe("serve.flush_seconds", flush_s)
            telemetry.observe("serve.batch_changes", n_changes)
            telemetry.record(
                "serve.flush", outcome="applied", flush=seq, changes=n_changes
            )
        with self._work:
            self._work.notify_all()  # blocked submitters + drain waiters

    def _resolve_subs(self, subs, out, seq, flush_s: float) -> None:
        """Split each replica's positioned stream into per-submission
        patch lists (ranges are ascending per replica in admission order —
        one pointer walk per replica) and resolve the futures."""
        ptr: Dict[str, int] = {}
        now = time.perf_counter()
        window = self._deadline_s + flush_s
        misses = 0
        for sub in subs:
            pairs = out[sub.session.replica]
            i = ptr.get(sub.session.replica, 0)
            lo, hi = sub._range
            start = i
            while i < len(pairs) and pairs[i][0] < hi:
                i += 1
            ptr[sub.session.replica] = i
            patches = [p for _, p in pairs[start:i]]
            sub.flush_seq = seq
            log = sub.session.patch_log
            if log is not None:
                log.extend(patches)
            sub._resolve(patches)
            elapsed = now - sub.t0
            if telemetry.enabled:
                telemetry.observe("e2e.admit_to_applied", elapsed)
                if sub.lat_class is not None:
                    telemetry.observe(
                        f"e2e.admit_to_applied_{sub.lat_class}", elapsed
                    )
            if elapsed > window:
                misses += 1
                self.stats["deadline_misses"] += 1
                if telemetry.enabled:
                    telemetry.counter("serve.deadline_miss")
            telemetry.flow_point(sub.ctx, terminal=True)
        # Storm detection: a sustained run of deadline misses is the
        # "serving plane is drowning" post-mortem moment.
        if misses:
            self._miss_streak += misses
            if self._miss_streak >= _MISS_STORM and not self._storm_dumped:
                self._storm_dumped = True
                telemetry.blackbox_dump(
                    "serve_deadline_storm",
                    plane=self.name,
                    consecutive_misses=self._miss_streak,
                    deadline_ms=self._deadline_s * 1000.0,
                )
        else:
            self._miss_streak = 0
            self._storm_dumped = False

    # -- breaker routing -----------------------------------------------------

    def _holding_locked(self) -> bool:
        if self._on_open != ON_OPEN_HOLD:
            return False
        br = health.breaker("device_launch")
        return br is not None and br.state == health.OPEN

    def _pop_all_locked(self) -> List[Submission]:
        popped: List[Submission] = []
        for s in self._sessions.values():
            popped.extend(s._lane)
            s._lane = []
            s._pending = 0
        return popped

    # -- driving -------------------------------------------------------------

    def step(self) -> bool:
        """Form and flush one cohort on the calling thread (manual mode;
        the scheduler thread calls this too).  Returns True when a flush —
        or a hold-policy shed — happened, False when there was nothing
        dispatchable (empty lanes, everything causally deferred, or the
        hold policy parking a cohort inside its deadline)."""
        with self._work:
            if self._holding_locked():
                _, age = self._depth_oldest_locked()
                if age <= self._deadline_s:
                    self.stats["held"] += 1
                    if telemetry.enabled:
                        telemetry.counter("serve.held")
                    return False
                shed = self._pop_all_locked()
                self._work.notify_all()
            else:
                shed = None
                formed = self._form_locked()
                if formed is not None:
                    # Mark the launch in flight BEFORE releasing the lock:
                    # run_quiesced holds _work and waits for this flag, so
                    # no universe mutation can interleave with the flush.
                    self._flush_busy = True
        if shed is not None:
            if shed:
                with telemetry.span("serve.hold_shed", plane=self.name):
                    self._reject_shed(
                        shed,
                        "device_launch breaker open past the "
                        f"{self._deadline_s * 1000:.0f}ms deadline (hold policy)",
                    )
            return bool(shed)
        if formed is None:
            return False
        try:
            self._flush(formed)
        finally:
            with self._work:
                self._flush_busy = False
                self._work.notify_all()
        return True

    def shape_keys(self) -> frozenset:
        """The distinct cohort shape keys this plane has flushed — the
        compile-cache pressure proxy.  The sharded plane unions these
        across shards (equal-width shards share programs process-wide)."""
        with self._lock:
            return frozenset(self._shapes)

    def run_quiesced(self, fn):
        """Run ``fn`` while no cohort launch is in flight and none can
        start (cohort formation takes the same lock this holds).  The
        sharded plane routes universe mutations — replica add/drop,
        mesh resharding — through this barrier: they rebuild the device
        state a concurrent launch would be reading."""
        with self._work:
            while self._flush_busy:
                self._work.wait()
            return fn()

    def drain(self, max_steps: int = 1000) -> int:
        """Flush until every lane empties or no progress is possible
        (manual mode).  Returns the number of still-pending submissions
        (0 = fully drained; >0 means causally-undeliverable leftovers)."""
        for _ in range(max_steps):
            with self._lock:
                if not any(s._lane for s in self._sessions.values()):
                    return 0
            if not self.step():
                break
        with self._lock:
            return sum(len(s._lane) for s in self._sessions.values())

    # -- the scheduler thread ------------------------------------------------

    def start(self) -> None:
        """Start the scheduler thread (idempotent)."""
        with self._lock:
            if self._thread is not None or self._closed:
                return
            self._thread = threading.Thread(
                target=self._run, name=f"peritext-{self.name}-scheduler", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._work:
                while True:
                    if self._closed:
                        return
                    depth, age = self._depth_oldest_locked()
                    if depth == 0:
                        self._work.wait(0.05)
                        continue
                    if (
                        depth >= self._batch_target
                        or age >= self._deadline_s
                        or self._drain_req
                    ):
                        break
                    self._work.wait(max(0.001, self._deadline_s - age))
            try:
                worked = self.step()
            except Exception:
                # The failed flush already rejected its submissions; the
                # scheduler must survive to serve the next cohort.
                worked = True
                _log.warning(
                    "serving plane %s flush failed; submissions carry the "
                    "error", self.name, exc_info=True,
                )
            if not worked:
                # Pending work past the deadline but nothing dispatchable
                # (everything causally deferred, or the hold policy parking
                # a cohort): without a wait the loop would spin hot re-
                # scanning the lanes.  A fresh submit notifies _work, so
                # the gap-filling change still wakes us immediately.
                with self._work:
                    self._work.wait(max(0.001, self._deadline_s))

    def flush_and_wait(self, timeout: float = 30.0) -> None:
        """Threaded-mode drain: ask the scheduler to flush everything
        pending and wait until the lanes are empty AND no flush is in
        flight.  (Admitted submissions leave their lane at cohort
        FORMATION, before the launch — an empty lane alone does not mean
        the last cohort's effects are visible, which bites callers that
        submitted without wait=True.)"""
        deadline = time.monotonic() + timeout
        with self._work:
            self._drain_req += 1
            self._work.notify_all()
            try:
                while (
                    any(s._lane for s in self._sessions.values())
                    or self._flush_busy
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"serving plane {self.name!r} did not drain "
                            f"within {timeout}s"
                        )
                    self._work.wait(min(remaining, 0.05))
            finally:
                self._drain_req -= 1

    def close(self, reject_pending: bool = True) -> None:
        """Stop the plane.  Pending submissions resolve with
        :class:`ServeClosedError` (``reject_pending=False`` leaves them
        unresolved for a caller that already drained)."""
        with self._work:
            self._closed = True
            self._work.notify_all()
            leftover = self._pop_all_locked() if reject_pending else []
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        if leftover:
            # Inside a span so the terminal flow events bind to a slice.
            with telemetry.span("serve.close", pending=len(leftover)):
                for sub in leftover:
                    sub._reject(ServeClosedError(
                        f"serving plane {self.name!r} closed with the "
                        "submission pending"
                    ))
                    telemetry.flow_point(sub.ctx, terminal=True, outcome="closed")

    def __enter__(self) -> "ServePlane":
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.close()
        return False
