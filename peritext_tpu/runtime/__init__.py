"""Replication runtime: queues, pub/sub, change logs, anti-entropy sync.

The host-side control plane of the framework — the equivalents of the
reference's pubsub.ts / changeQueue.ts / test-merge.ts layer (SURVEY.md §2.4).
The data plane (batched op application) lives in ``peritext_tpu.ops``.
"""
from peritext_tpu.runtime import faults, health, slo, telemetry
from peritext_tpu.runtime.faults import FaultError, FaultPlan
from peritext_tpu.runtime.health import BreakerOpenError, CircuitBreaker, HealthPlan
from peritext_tpu.runtime.slo import SloObjective, SloPlan
from peritext_tpu.runtime.log import ChangeLog
from peritext_tpu.runtime.pubsub import Publisher
from peritext_tpu.runtime.queue import ChangeQueue, QueueFullError
from peritext_tpu.runtime.serve import (
    ServeClosedError,
    ServePlane,
    ServeSession,
    ServeShedError,
    Submission,
)
from peritext_tpu.runtime.serve_shard import ShardedServePlane, ShardSession
from peritext_tpu.runtime.sync import (
    ConvergenceError,
    apply_available,
    apply_changes,
    causal_order,
    causal_sort,
    sync_pair,
)

__all__ = [
    "BreakerOpenError",
    "ChangeLog",
    "ChangeQueue",
    "CircuitBreaker",
    "ConvergenceError",
    "FaultError",
    "FaultPlan",
    "HealthPlan",
    "Publisher",
    "QueueFullError",
    "ServeClosedError",
    "ServePlane",
    "ServeSession",
    "ServeShedError",
    "ShardSession",
    "ShardedServePlane",
    "SloObjective",
    "SloPlan",
    "Submission",
    "apply_available",
    "apply_changes",
    "causal_order",
    "causal_sort",
    "faults",
    "health",
    "slo",
    "sync_pair",
    "telemetry",
]
