"""Benchmark workloads (BASELINE.json configs)."""
