"""Benchmark runner subprocess: measures and prints the one JSON line.

Invoked by bench.py (possibly with PERITEXT_BENCH_PLATFORM=cpu as a fallback
when the TPU tunnel is unreachable — bench.py supervises with a timeout).
"""
import json
import os
import sys


def _emit(result: dict) -> None:
    """Stamp the current telemetry summary and print the JSON line.

    EVERY line this runner prints goes through here, so the salvage path
    (bench.py keeps the last complete line of a killed child) always
    recovers the telemetry the run had accumulated by that point —
    retries, degraded batches, and merge-path tallies survive a wedged
    relay exactly like the headline number does."""
    from peritext_tpu.runtime import health, slo, telemetry

    summary = telemetry.summary()
    # The serving-plane tallies get their own top-level stamp (admission/
    # batching/shed behavior + compile-cache hit rate) so serve A/B runs
    # can diff it without digging through the telemetry block.
    serve_summary = summary.pop("serve", None) if summary else None
    if serve_summary:
        result["serve"] = serve_summary
    if summary:
        summary.pop("slo", None)  # the dedicated block below supersedes it
        result["telemetry"] = summary
    # Health-plane summary (breaker states, trip/fastfail/canary tallies)
    # rides the same salvage contract: present on every line whenever a
    # PERITEXT_BREAKER plan is active.
    health_summary = health.summary()
    if health_summary:
        result["health"] = health_summary
    # SLO-plane verdicts (burn/compliance/breach per objective): present
    # on every line whenever a PERITEXT_SLO plan is active, so the
    # salvage path recovers the objective state a wedged run reached.
    slo_summary = slo.summary()
    if slo_summary:
        result["slo"] = slo_summary
    print(json.dumps(result))
    sys.stdout.flush()


def main() -> None:
    platform = os.environ.get("PERITEXT_BENCH_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    # Registry-only collection (the counters are launch-level — noise vs
    # the merge work being measured); PERITEXT_TRACE/PERITEXT_METRICS in
    # the env additionally activate the tracer / exit dump as usual.
    from peritext_tpu.runtime import telemetry

    telemetry.enable()

    num_replicas = int(os.environ.get("BENCH_REPLICAS", "1024"))
    doc_len = int(os.environ.get("BENCH_DOC_LEN", "1000"))
    ops_per_merge = int(os.environ.get("BENCH_OPS", "64"))

    from peritext_tpu.bench.workloads import time_batched_merge, time_scalar_baseline

    def measure():
        return time_batched_merge(
            num_replicas=num_replicas, doc_len=doc_len, ops_per_merge=ops_per_merge
        )

    def measure_with_fallback():
        # The sorted placement path is newer than the scan path's hardware
        # record; if it fails to compile/execute on this backend, retry the
        # same measurement on the sequential scan path rather than losing
        # the platform entirely (bench.py's platform fallback is the outer
        # guard).
        if os.environ.get("BENCH_PALLAS") == "1":
            return measure(), "pallas"  # BENCH_PALLAS wins in workloads.py
        if os.environ.get("BENCH_PATH") == "scan":
            return measure(), "scan"
        try:
            return measure(), "sorted"
        except Exception as err:  # compile/lowering failure on this backend
            print(f"bench: sorted path failed ({type(err).__name__}: {err}); "
                  "retrying on the scan path", file=sys.stderr)
            os.environ["BENCH_PATH"] = "scan"
            return measure(), "scan_fallback"

    # The scalar baseline is pure host work — measure it BEFORE first device
    # contact so the headline line can print complete the moment the device
    # measurement lands (a relay wedge later must not cost the number).
    scalar = time_scalar_baseline(doc_len=doc_len, ops_per_merge=ops_per_merge)

    profile_dir = os.environ.get("PERITEXT_PROFILE")
    if profile_dir:
        # SURVEY §5 observability: capture a device trace of one measured
        # round (XLA op timeline + HBM traffic on TPU backends).  View with
        # tensorboard / xprof; the artifact dir is the deliverable.
        import jax

        with jax.profiler.trace(profile_dir):
            tpu, path = measure_with_fallback()
    else:
        tpu, path = measure_with_fallback()

    import jax

    from peritext_tpu.bench.conditions import measurement_conditions

    result = {
        "metric": "merged_crdt_ops_per_sec_batched_replicas",
        "value": round(tpu["ops_per_sec"], 1),
        "unit": "ops/s",
        "vs_baseline": round(tpu["ops_per_sec"] / scalar["ops_per_sec"], 2),
        "platform": jax.devices()[0].platform,
        "path": path,
        "best_of": tpu.get("best_of", 1),
        "conditions": measurement_conditions(platform=jax.devices()[0].platform),
    }
    # Salvage point: the headline throughput is safe on stdout NOW; if the
    # relay wedges during the latency measurement below, the supervisor
    # (bench.py) recovers this line from the killed child's output.  The
    # final print supersedes it (last JSON line wins).
    _emit(result)

    # BASELINE's second tracked metric: p50 merge latency @ 10k-char doc.
    try:
        from peritext_tpu.bench.workloads import time_merge_latency

        latency = time_merge_latency()
    except Exception as err:
        print(f"bench: latency measurement failed: {err}", file=sys.stderr)
        latency = None

    if latency is not None:
        result["p50_merge_latency_ms_10k_doc"] = latency["p50_ms"]
        result["latency_path"] = latency["path"]
        _emit(result)

    # Opt-in third metric: the PATCH-EMITTING ingest path (what an editor
    # fleet consumes), end-to-end through the universe API.  BENCH_PATCHES=1
    # adds it; =ab also measures the interleaved-scan fallback for the A/B.
    patches_mode = os.environ.get("BENCH_PATCHES")
    if patches_mode:
        try:
            from peritext_tpu.bench.workloads import time_patched_merge

            p = time_patched_merge()
            result["patched_ops_per_sec"] = round(p["ops_per_sec"], 1)
            result["patched_replicas"] = p["replicas"]
            result["patched_path"] = p["path"]
            # Per-leg D2H record bytes from the telemetry tally: the
            # compact readback's target metric (ISSUE 8) — stamped on
            # every patched leg so A/B runs carry it in one JSON line.
            if p.get("d2h_bytes") is not None:
                result["patched_d2h_bytes"] = p["d2h_bytes"]
            # One fresh-universe ingest measures the cache-COLD regime
            # (dominance init included); the editor-fleet steady state is
            # cache-WARM (time_patched_fleet below).
            result["patched_regime"] = "cold_single_ingest"
            # The common pure-typing ingest (no mark rows): compiles the
            # static mark-free fast path, no winner-cache init or scan.
            p_typing = time_patched_merge(with_marks=False)
            result["patched_typing_ops_per_sec"] = round(p_typing["ops_per_sec"], 1)
            if patches_mode == "ab":
                p_scan = time_patched_merge(force_scan=True)
                result["patched_scan_ops_per_sec"] = round(p_scan["ops_per_sec"], 1)
                # Salvage point: a BENCH_TIMEOUT kill during the dense leg
                # must not discard the three legs already measured.
                _emit(result)
                # The full-plane-carry sorted scan, for the compact-delta
                # A/B at the single-ingest shape (fleet legs below A/B the
                # steady state).
                p_dense = time_patched_merge(mode="dense")
                result["patched_dense_ops_per_sec"] = round(
                    p_dense["ops_per_sec"], 1
                )
                # Compact-vs-planes readback A/B at the single-ingest
                # shape (same stream; only the record transfer differs).
                p_planes = time_patched_merge(readback="planes")
                result["patched_planes_ops_per_sec"] = round(
                    p_planes["ops_per_sec"], 1
                )
                if p_planes.get("d2h_bytes") is not None:
                    result["patched_planes_d2h_bytes"] = p_planes["d2h_bytes"]
            _emit(result)
        except Exception as err:
            print(f"bench: patched measurement failed: {err}", file=sys.stderr)
        # Editor-fleet steady state (VERDICT r4 item 4): cache-cold vs
        # cache-warm on ONE universe, plus the no-patch gap.  Its own try +
        # incremental print, so a failure or supervisor timeout here can
        # never discard the patched legs already on stdout above.
        try:
            from peritext_tpu.bench.workloads import time_patched_fleet

            fleet = time_patched_fleet()
            result["patched_cold_ops_per_sec"] = round(
                fleet["patched_cold_ops_per_sec"], 1
            )
            result["patched_warm_ops_per_sec"] = round(
                fleet["patched_warm_ops_per_sec"], 1
            )
            result["fleet_no_patch_ops_per_sec"] = round(
                fleet["no_patch_ops_per_sec"], 1
            )
            result["warm_vs_no_patch"] = round(fleet["warm_vs_no_patch"], 3)
            result["fleet_path"] = fleet["path"]
            if fleet.get("warm_d2h_bytes") is not None:
                result["fleet_cold_d2h_bytes"] = fleet["cold_d2h_bytes"]
                result["fleet_warm_d2h_bytes"] = fleet["warm_d2h_bytes"]
            _emit(result)
        except Exception as err:
            print(f"bench: fleet measurement failed: {err}", file=sys.stderr)
        # BENCH_PATCHES=ab: the dense-vs-delta fleet legs in ONE run —
        # identical streams (same seed), same universe lifecycle, only the
        # mark-row scan differs.  Incremental print again: a timeout here
        # keeps every leg already emitted.
        if patches_mode == "ab":
            try:
                from peritext_tpu.bench.workloads import time_patched_fleet

                dense = time_patched_fleet(mode="dense")
                result["fleet_dense_cold_ops_per_sec"] = round(
                    dense["patched_cold_ops_per_sec"], 1
                )
                result["fleet_dense_warm_ops_per_sec"] = round(
                    dense["patched_warm_ops_per_sec"], 1
                )
                result["fleet_dense_warm_vs_no_patch"] = round(
                    dense["warm_vs_no_patch"], 3
                )
                warm = result.get("patched_warm_ops_per_sec")
                if warm:
                    result["fleet_delta_vs_dense_warm"] = round(
                        warm / dense["patched_warm_ops_per_sec"], 3
                    )
                _emit(result)
            except Exception as err:
                print(
                    f"bench: dense fleet A/B measurement failed: {err}",
                    file=sys.stderr,
                )
            # Compact-vs-planes readback fleet A/B (identical streams,
            # only the record transfer format differs): the D2H cut and
            # its throughput effect at the steady state, in the same run.
            try:
                from peritext_tpu.bench.workloads import time_patched_fleet

                planes = time_patched_fleet(readback="planes")
                result["fleet_planes_warm_ops_per_sec"] = round(
                    planes["patched_warm_ops_per_sec"], 1
                )
                if planes.get("warm_d2h_bytes") is not None:
                    result["fleet_planes_warm_d2h_bytes"] = planes[
                        "warm_d2h_bytes"
                    ]
                    warm_d2h = result.get("fleet_warm_d2h_bytes")
                    if warm_d2h:
                        result["fleet_d2h_cut_vs_planes"] = round(
                            planes["warm_d2h_bytes"] / warm_d2h, 2
                        )
                warm = result.get("patched_warm_ops_per_sec")
                if warm:
                    result["fleet_compact_vs_planes_warm"] = round(
                        warm / planes["patched_warm_ops_per_sec"], 3
                    )
                _emit(result)
            except Exception as err:
                print(
                    f"bench: planes readback fleet A/B measurement failed: {err}",
                    file=sys.stderr,
                )
            # Windowed-vs-full merge A/B (ISSUE 12): single-op latency at
            # the tracked 10k-doc shape through the full universe API,
            # identical seeded edit streams, digest-asserted identity.
            try:
                from peritext_tpu.bench.workloads import time_window_single_op

                w_leg = time_window_single_op(windowed=True)
                f_leg = time_window_single_op(windowed=False)
                assert w_leg["digest"] == f_leg["digest"], "window A/B diverged"
                result["windowed_p50_ms_10k_doc"] = w_leg["p50_ms"]
                result["full_table_p50_ms_10k_doc"] = f_leg["p50_ms"]
                if w_leg["p50_ms"]:
                    result["window_p50_cut"] = round(
                        f_leg["p50_ms"] / w_leg["p50_ms"], 2
                    )
                result["windowed_launches_10k"] = w_leg["windowed_launches"]
                _emit(result)
            except Exception as err:
                print(
                    f"bench: windowed merge A/B measurement failed: {err}",
                    file=sys.stderr,
                )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    main()
