"""The five BASELINE.json benchmark configs as one runnable module.

Each config returns a JSON-able record; ``python -m peritext_tpu.bench.configs
--config N`` prints it.  Configs 1-2 exercise the reference-model path (the
bridge/oracle at human scale); configs 3-5 are the batched device workloads
(BASELINE.md):

1. 2-replica ``traces/links-minimal.json`` replay via the document API.
2. fuzz-shaped random edit trace, 2 replicas x 1k ops, plain text.
3. 1k-replica batched merge, 1k-char docs, insert/delete only.
4. 10k-replica batched merge with overlapping marks.
5. 100k-replica 10k-char docs, mixed marks, multi-chip mesh.  The full
   shape needs a v5e-8's HBM; ``scale="small"`` (default off-hardware) runs
   the same *shape* scaled down on whatever mesh exists so the codepath is
   exercised end-to-end, and reports the scale it actually ran.
6. editor-fleet patched-ingest steady state (the workload the north star
   serves): repeated apply_changes_with_patches rounds on one universe,
   cold/warm split.  Honors ``PERITEXT_PATCH_PATH`` (compact-delta scan
   by default; ``dense`` pins the full-plane A/B baseline, ``scan`` the
   interleaved fallback), so the dense-vs-delta A/B is two invocations
   of the same config.
7. serving-plane steady state: continuous batching vs naive per-change
   ingest on identical traffic (runtime/serve.py).
8. mesh-sharded serving: identical traffic through 1 vs K universe
   shards (runtime/serve_shard.py), scaling curve + shape-bucket bound.

Env knobs: CONFIG5_REPLICAS / CONFIG5_DOC_LEN override config 5's scale;
CONFIG6_REPLICAS / CONFIG6_ROUNDS config 6's; CONFIG7_SESSIONS / ROUNDS /
CHANGES config 7's; CONFIG8_SHARDS / SESSIONS / ROUNDS / CHANGES /
DOC_LEN config 8's.
"""
from __future__ import annotations

import json
import os
import random
import time
from typing import Any, Dict

REFERENCE_TRACES = "/root/reference/traces"


def config1_trace_replay() -> Dict[str, Any]:
    """Replay the reference's links-minimal failure trace through both
    engines (the reference-model workload: 2 replicas over the wire)."""
    path = os.path.join(REFERENCE_TRACES, "links-minimal.json")
    with open(path) as f:
        trace = json.load(f)
    from peritext_tpu.oracle import Doc
    from peritext_tpu.runtime.sync import apply_changes

    queues = trace["queues"]
    start = time.perf_counter()
    docs = {actor: Doc(actor) for actor in queues}
    total = 0
    for actor, doc in docs.items():
        for other, changes in queues.items():
            applied = apply_changes(doc, [dict(c) for c in changes])
            total += len(applied)
    elapsed = time.perf_counter() - start
    spans = [d.get_text_with_formatting(["text"]) for d in docs.values()]
    assert all(s == spans[0] for s in spans[1:]), "trace replay diverged"
    return {
        "config": 1,
        "workload": "links-minimal trace replay (oracle, 2 replicas)",
        "changes_applied": total,
        "seconds": round(elapsed, 4),
        "changes_per_sec": round(total / elapsed, 1),
    }


def config2_fuzz_style(ops: int = 1000, seed: int = 11) -> Dict[str, Any]:
    """Random plain-text edit trace, 2 replicas, sync at the end."""
    from peritext_tpu.fuzz import _random_delete, _random_insert
    from peritext_tpu.ops import TpuUniverse
    from peritext_tpu.oracle import Doc
    from peritext_tpu.testing import generate_docs

    rng = random.Random(seed)
    docs, _, genesis = generate_docs("fuzz config", count=2)
    changes = {d.actor_id: [] for d in docs}
    budget = ops
    while budget > 0:
        doc = rng.choice(docs)
        op = (_random_insert(rng, doc, 3) if rng.random() < 0.6 else _random_delete(rng, doc))
        if op is None:
            continue
        change, _ = doc.change([op])
        changes[doc.actor_id].append(change)
        budget -= len(change["ops"])

    stream = changes["doc1"] + changes["doc2"]

    def run():
        uni = TpuUniverse(["a", "b"], capacity=1024)
        uni.apply_changes({"a": [genesis], "b": [genesis]})
        start = time.perf_counter()
        uni.apply_changes({"a": stream, "b": list(reversed_pairs(stream))})
        digests = uni.digests()
        elapsed = time.perf_counter() - start
        assert digests[0] == digests[1], "config2 diverged"
        return elapsed

    run()  # warm the jit caches (same shapes) untimed
    elapsed = run()
    n_ops = sum(len(c["ops"]) for c in stream)
    return {
        "config": 2,
        "workload": "fuzz-style random edits, 2 replicas, ~1k internal ops",
        "internal_ops": 2 * n_ops,
        "seconds": round(elapsed, 4),
        "ops_per_sec": round(2 * n_ops / elapsed, 1),
    }


def reversed_pairs(stream):
    """Deliver causally-valid per-actor order but interleave actors
    differently on the second replica (order-independence check)."""
    a = [c for c in stream if c["actor"] == "doc1"]
    b = [c for c in stream if c["actor"] == "doc2"]
    out = []
    for i in range(max(len(a), len(b))):
        if i < len(b):
            out.append(b[i])
        if i < len(a):
            out.append(a[i])
    return out


def config3_batched_plain(replicas: int = 1024) -> Dict[str, Any]:
    from peritext_tpu.bench.workloads import time_batched_merge

    r = time_batched_merge(num_replicas=replicas, doc_len=1000, ops_per_merge=64,
                           with_marks=False, rounds=8)
    return {
        "config": 3,
        "workload": f"{replicas}-replica batched merge, 1k-char docs, insert/delete",
        "ops_per_sec": round(r["ops_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "total_ops": r["total_ops"],
    }


def config4_batched_marked(replicas: int = 10240) -> Dict[str, Any]:
    from peritext_tpu.bench.workloads import time_batched_merge

    r = time_batched_merge(num_replicas=replicas, doc_len=1000, ops_per_merge=64,
                           with_marks=True, rounds=4)
    return {
        "config": 4,
        "workload": f"{replicas}-replica batched merge with overlapping marks",
        "ops_per_sec": round(r["ops_per_sec"], 1),
        "seconds": round(r["seconds"], 4),
        "total_ops": r["total_ops"],
    }


def config5_multichip(replicas: int | None = None, doc_len: int | None = None) -> Dict[str, Any]:
    """Config-5 *shape*: long docs + marks, replica batch sharded over the
    mesh, merge + convergence reduce + sequence-parallel flatten.

    The headline shape (100k x 10k chars) needs a v5e-8; scale defaults fit
    the machine at hand (env CONFIG5_REPLICAS / CONFIG5_DOC_LEN override —
    the driver's v5e-8 run uses 100000 / 10000).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from peritext_tpu.bench.workloads import build_device_batch, make_merge_workload
    from peritext_tpu.ops import kernels as K
    from peritext_tpu.ops.encode import prepare_sorted_batch
    from peritext_tpu.parallel import make_mesh, shard_states
    from peritext_tpu.parallel.shard import flatten_sources_sp
    from peritext_tpu.schema import allow_multiple_array

    n_dev = len(jax.devices())
    replicas = replicas or int(os.environ.get("CONFIG5_REPLICAS", 8 * n_dev))
    doc_len = doc_len or int(os.environ.get("CONFIG5_DOC_LEN", "4096"))
    capacity = 1
    while capacity < doc_len + 512:
        capacity *= 2

    # CONFIG5_STREAM_COHORT=N runs the streaming-cohort route (the
    # north-star path past the HBM residency wall, parallel/stream.py):
    # the population lives host-side and cohorts of N replicas stream
    # through the mesh, double-buffered.  Warmup compiles the one
    # cohort-shaped program on a single cohort; the measured pass streams
    # the full population with fresh op ids.
    stream_cohort = int(os.environ.get("CONFIG5_STREAM_COHORT", "0"))

    n_streams = 4
    workload = make_merge_workload(doc_len=doc_len, ops_per_merge=64,
                                   num_streams=n_streams, with_marks=True, seed=5)
    # In streaming mode the device only ever sees one cohort: build the
    # base state + the n_streams distinct op streams at n_streams rows and
    # tile HOST-side — a beyond-residency population must never be
    # materialized device-resident, which is the route's whole point.
    batch = build_device_batch(
        workload, n_streams if stream_cohort else replicas, capacity, 128
    )
    seq = 2 if n_dev % 2 == 0 and n_dev >= 4 else 1
    mesh = make_mesh(jax.devices()[: (n_dev // seq) * seq], n_dev // seq, seq)

    # Host prep runs once per distinct stream; one gather tiles it to R
    # (the same trick as TpuUniverse._prepare — never per-replica Python).
    tile = np.arange(replicas) % n_streams
    sp = prepare_sorted_batch([batch["text_ops"][r] for r in range(n_streams)])
    text_np = sp["text"][tile]
    rounds_np = sp["rounds"][tile]
    bufs_np = sp["bufs"][tile]

    if stream_cohort:
        from peritext_tpu.bench.conditions import measurement_conditions
        from peritext_tpu.bench.workloads import shift_op_ids
        from peritext_tpu.parallel.stream import stream_merge_sorted

        genesis_max = workload["genesis"]["startOp"] + len(workload["genesis"]["ops"]) - 1
        # Every replica starts from the same base state: the host
        # population is a zero-copy broadcast view of row 0 (the stream
        # executor copies per cohort at device_put time).
        states_np = jax.tree.map(
            lambda a: np.broadcast_to(np.asarray(a[:1]), (replicas,) + a.shape[1:]),
            batch["states"],
        )
        batch["states"] = None  # free the device-resident copy
        marks_np = batch["mark_ops"][tile]
        # Per-replica op counts for the tiled population (rows are
        # zero-padded; K_KIND=0 is inert padding).
        per_stream = np.asarray(
            [
                (batch["text_ops"][s][:, K.K_KIND] != 0).sum()
                + (batch["mark_ops"][s][:, K.K_KIND] != 0).sum()
                for s in range(n_streams)
            ]
        )
        stream_total_ops = int(per_stream[tile].sum())

        def stream(shift, rows, readback):
            return stream_merge_sorted(
                jax.tree.map(lambda a: a[:rows], states_np),
                shift_op_ids(text_np[:rows], shift, genesis_max),
                rounds_np[:rows],
                sp["num_rounds"],
                shift_op_ids(marks_np[:rows], shift, genesis_max),
                batch["ranks"],
                bufs_np[:rows],
                sp["maxk"],
                cohort=stream_cohort,
                mesh=mesh,
                readback_states=readback,
            )

        # CONFIG5_STREAM_READBACK=1 includes the full per-cohort D2H state
        # readback in the timed pass (the population-update round trip);
        # the default 0 measures the digest-only convergence sweep — at
        # north-star scale a dense host copy of the OUTPUT population is
        # its own resource question (the input rides a broadcast here).
        readback = os.environ.get("CONFIG5_STREAM_READBACK", "0") == "1"
        stream(1_000_000, min(stream_cohort, replicas), readback)  # compile
        start = time.perf_counter()
        out_states, digests, stats = stream(2_000_000, replicas, readback)
        merge_s = time.perf_counter() - start
        if not readback:
            # Recover just the flatten leg's cohort (same op-id shift, so
            # these states equal the timed pass's first-cohort output).
            # Use the EFFECTIVE cohort (stats) — it may have been rounded
            # up to the replica mesh axis.
            out_states, _, _ = stream(
                2_000_000, min(stats["cohort"], replicas), True
            )
        for r in range(n_streams, replicas):
            assert digests[r] == digests[r % n_streams], "config5 stream diverged"

        # Flatten one resident cohort of the streamed output (the flatten
        # leg of a streaming pass is per-cohort by construction).  The
        # effective cohort (stats) is already a replica-axis multiple; clamp
        # to the population by padding with row 0, mirroring the stream's
        # own tail handling, so shard_states always divides evenly.
        avail = min(
            stats["cohort"], replicas, jax.tree.leaves(out_states)[0].shape[0]
        )
        rows = -(-avail // int(mesh.shape["replica"])) * int(mesh.shape["replica"])

        def cohort_rows(a):
            sl = np.asarray(a[:avail])
            if rows > avail:
                fill = np.broadcast_to(sl[0:1], (rows - avail,) + sl.shape[1:])
                sl = np.concatenate([sl, fill], axis=0)
            return jnp.asarray(sl)

        cohort_states = shard_states(jax.tree.map(cohort_rows, out_states), mesh)
        flatten = flatten_sources_sp(mesh)

        def flatten_cohort():
            mask, has = flatten(
                cohort_states.deleted,
                cohort_states.bnd_def,
                cohort_states.bnd_mask,
                cohort_states.length,
            )
            np.asarray(has)

        flatten_cohort()  # compile
        start = time.perf_counter()
        flatten_cohort()
        flatten_s = time.perf_counter() - start

        total_ops = stream_total_ops
        return {
            "config": 5,
            "merge": "streaming_cohorts",
            "workload": f"{replicas} replicas x {doc_len}-char docs, mixed marks, "
            f"streamed in {stats['n_cohorts']} cohorts of {stats['cohort']} "
            f"over mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}",
            "merge_ops_per_sec": round(total_ops / merge_s, 1),
            "merge_seconds": round(merge_s, 4),
            "cohort": stats["cohort"],
            "n_cohorts": stats["n_cohorts"],
            "state_readback_timed": readback,
            # Numerator counts only the AVAILABLE cohort rows (ADVICE r5):
            # `rows` is padded up to the replica mesh axis with row-0
            # duplicates, which do cost flatten time but are not real
            # population throughput.  Both counts are emitted so the pad
            # overhead stays visible.
            "flatten_chars_per_sec_per_cohort": round(avail * doc_len / flatten_s, 1),
            "flatten_rows": rows,
            "flatten_avail": avail,
            "platform": jax.devices()[0].platform,
            "conditions": measurement_conditions(),
            "note": "streaming-cohort route: aggregate replicas decoupled from "
            "device residency (BASELINE.md north-star route)",
        }

    base_states = shard_states(batch["states"], mesh)
    ranks = jnp.asarray(batch["ranks"])
    multi = jnp.asarray(allow_multiple_array())

    # CONFIG5_EXPLICIT_SP=1 runs the explicit-collective merge (pmin +
    # ppermute halo placement) instead of the GSPMD-auto sorted merge.
    explicit_sp = os.environ.get("CONFIG5_EXPLICIT_SP") == "1"
    if explicit_sp:
        from peritext_tpu.parallel.shard import merge_step_sorted_sp

        budget = int(
            (text_np[..., K.K_KIND] == K.KIND_INSERT).sum(axis=1).max()
            + (
                text_np[..., K.K_RUN_LEN]
                * (text_np[..., K.K_KIND] == K.KIND_INSERT_RUN)
            ).sum(axis=1).max()
        )
        halo = 8
        while halo < budget:
            halo *= 2
        sp_merge = merge_step_sorted_sp(mesh, halo=halo, maxk=sp["maxk"])

    def merge_and_digest(states, shift):
        # Distinct op ids per invocation (counters shifted; refs into the
        # genesis doc untouched) so no layer can serve cached results.
        from peritext_tpu.bench.workloads import shift_op_ids

        genesis_max = workload["genesis"]["startOp"] + len(workload["genesis"]["ops"]) - 1
        text = shift_op_ids(text_np, shift, genesis_max)
        marks = shift_op_ids(batch["mark_ops"], shift, genesis_max)
        if explicit_sp:
            out = sp_merge(
                states,
                jnp.asarray(text),
                jnp.asarray(rounds_np),
                jnp.int32(sp["num_rounds"]),
                jnp.asarray(marks),
                ranks,
                jnp.asarray(bufs_np),
            )
        else:
            out = K.merge_step_sorted_batch(
                states,
                jnp.asarray(text),
                jnp.asarray(rounds_np),
                sp["num_rounds"],
                jnp.asarray(marks),
                ranks,
                jnp.asarray(bufs_np),
                sp["maxk"],
            )
        return out, np.asarray(K.convergence_digest_batch(out, ranks, multi))

    flatten = flatten_sources_sp(mesh)

    def flatten_once(states):
        mask, has = flatten(states.deleted, states.bnd_def, states.bnd_mask, states.length)
        np.asarray(has)  # host readback barrier

    # Warm both programs (compile) untimed, then measure fresh-id runs.
    warm_states, _ = merge_and_digest(base_states, 0)
    flatten_once(warm_states)

    start = time.perf_counter()
    states, digests = merge_and_digest(base_states, 1_000_000)
    merge_s = time.perf_counter() - start
    for r in range(n_streams, replicas):
        assert digests[r] == digests[r % n_streams], "config5 diverged across shards"

    start = time.perf_counter()
    flatten_once(states)
    flatten_s = time.perf_counter() - start

    total_ops = batch["total_ops"]
    return {
        "config": 5,
        "merge": "explicit_sp" if explicit_sp else "gspmd_sorted",
        "workload": f"{replicas} replicas x {doc_len}-char docs, mixed marks, "
        f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}",
        "merge_ops_per_sec": round(total_ops / merge_s, 1),
        "merge_seconds": round(merge_s, 4),
        "flatten_chars_per_sec": round(replicas * doc_len / flatten_s, 1),
        "platform": jax.devices()[0].platform,
        "note": "headline shape is 100000 x 10000 on v5e-8; this run is the "
        "same shape at the scale this host fits"
        if replicas < 100_000
        else "headline shape",
    }


def config6_patched_fleet() -> Dict[str, Any]:
    """Editor-fleet patched steady state through the full universe API
    (gate, encode, device merge, record readback, host patch assembly).

    The mark-row scan variant follows PERITEXT_PATCH_PATH — unset runs
    the compact-delta default, ``dense`` the full-plane A/B baseline,
    ``scan`` the interleaved fallback — so A/B legs are plain re-runs of
    this config under different env.
    """
    from peritext_tpu.bench.workloads import time_patched_fleet

    knob = os.environ.get("PERITEXT_PATCH_PATH")
    mode = knob if knob in ("dense", "scan") else None
    r = time_patched_fleet(
        num_replicas=int(os.environ.get("CONFIG6_REPLICAS", "256")),
        rounds=int(os.environ.get("CONFIG6_ROUNDS", "4")),
        mode=mode,
        # CONFIG6_LOCALITY=N confines each round's edits to one N-char
        # hotspot (the editor-caret pattern): the regime where the
        # frontier-bounded window merge engages (PERITEXT_MERGE_WINDOW).
        # 0 (default) keeps the historical uniform-position baseline.
        locality=int(os.environ.get("CONFIG6_LOCALITY", "0")),
    )
    return {
        "config": 6,
        "workload": f"{r['replicas']}-replica editor fleet, {r['rounds']} patched "
        f"ingest rounds, {r['doc_len']}-char docs",
        "path": r["path"],
        "locality": r["locality"],
        "windowed_launches": r["windowed_launches"],
        "patched_cold_ops_per_sec": round(r["patched_cold_ops_per_sec"], 1),
        "patched_warm_ops_per_sec": round(r["patched_warm_ops_per_sec"], 1),
        "no_patch_ops_per_sec": round(r["no_patch_ops_per_sec"], 1),
        "warm_vs_no_patch": round(r["warm_vs_no_patch"], 3),
    }


def config7_serving_plane() -> Dict[str, Any]:
    """Serving-plane steady state: multi-session continuous batching vs
    naive per-change ingest on identical traffic (runtime/serve.py).

    The A/B legs share one authored traffic matrix (independent editors,
    one replica per session) and assert byte-identical per-session patch
    streams; the record is the throughput ratio, the admit-to-applied
    percentiles, and the compile-shape counts.  Env knobs:
    CONFIG7_SESSIONS / CONFIG7_ROUNDS / CONFIG7_CHANGES; the plane's own
    PERITEXT_SERVE_* knobs apply to the served leg.
    """
    from peritext_tpu.bench.workloads import time_serve_ab

    r = time_serve_ab(
        sessions=int(os.environ.get("CONFIG7_SESSIONS", "8")),
        rounds=int(os.environ.get("CONFIG7_ROUNDS", "8")),
        changes_per_round=int(os.environ.get("CONFIG7_CHANGES", "8")),
    )
    return {
        "config": 7,
        "workload": f"{r['sessions']}-session serving plane, {r['rounds']} "
        f"rounds x {r['changes_per_round']} changes/session, "
        f"{r['doc_len']}-char docs",
        "served_ops_per_sec": round(r["served_ops_per_sec"], 1),
        "naive_ops_per_sec": round(r["naive_ops_per_sec"], 1),
        "served_vs_naive": round(r["served_vs_naive"], 2),
        "served_launches": r["served_launches"],
        "naive_launches": r["naive_launches"],
        "served_p95_admit_to_applied_ms": round(
            r["served_p95_admit_to_applied_s"] * 1000, 2
        ),
        "served_p95_within_window": r["served_p95_within_window"],
        "served_compiled_shapes": r["served_compiled_shapes"],
        "naive_compiled_shapes": r["naive_compiled_shapes"],
    }


def config8_sharded_serving() -> Dict[str, Any]:
    """Mesh-sharded serving steady state: identical multi-session traffic
    through 1 vs K universe shards (runtime/serve_shard.py).

    The record is the served-throughput scaling curve (1-shard leg is the
    PR-10 single-plane shape; per-shard cohort launches sweep 1/K of the
    fleet rows for the same batch budget), per-session byte-identity
    asserted in-harness, and the fleet-wide compiled-shape bound the pow2
    shard buckets hold.  Env knobs: CONFIG8_SHARDS (comma list, default
    "1,8"), CONFIG8_SESSIONS / CONFIG8_ROUNDS / CONFIG8_CHANGES /
    CONFIG8_DOC_LEN; the planes' PERITEXT_SERVE_* knobs apply per shard.
    """
    from peritext_tpu.bench.workloads import time_serve_shard_ab

    shard_counts = [
        int(k) for k in os.environ.get("CONFIG8_SHARDS", "1,8").split(",")
    ]
    r = time_serve_shard_ab(
        sessions=int(os.environ.get("CONFIG8_SESSIONS", "64")),
        rounds=int(os.environ.get("CONFIG8_ROUNDS", "4")),
        changes_per_round=int(os.environ.get("CONFIG8_CHANGES", "8")),
        doc_len=int(os.environ.get("CONFIG8_DOC_LEN", "600")),
        shard_counts=shard_counts,
    )
    legs = {
        leg["shards"]: {
            "ops_per_sec": round(leg["ops_per_sec"], 1),
            # Relative to the FIRST configured leg (only a 1-shard
            # baseline when CONFIG8_SHARDS starts with 1, the default).
            "speedup_vs_first": round(leg["speedup_vs_first"], 2),
            "launches": leg["launches"],
            "fleet_compiled_shapes": leg["fleet_compiled_shapes"],
            "p95_admit_to_applied_ms": round(
                leg["p95_admit_to_applied_s"] * 1000, 2
            ),
        }
        for leg in r["legs"]
    }
    return {
        "config": 8,
        "workload": f"{r['sessions']}-session sharded serving, "
        f"{r['rounds']} rounds x {r['changes_per_round']} changes/session, "
        f"{r['doc_len']}-char docs, shards {shard_counts}",
        "baseline_shards": shard_counts[0],
        "byte_identity": r["byte_identity"],
        "shape_bound_ok": r["shape_bound_ok"],
        "legs": legs,
    }


def config9_elastic_serving() -> Dict[str, Any]:
    """Elastic serving under a load spike: static vs autoscaled shard
    fleet on identical traffic (runtime/elastic.py).

    Every session starts pinned to shard 0; the elastic leg's controller
    live-migrates the hot shard's sessions to cold shards between traffic
    bursts.  The record is the late-round p95 admit-to-applied recovery
    (elastic vs the static control), the migration tally, and the final
    session distribution — per-session byte-identity between the legs is
    asserted in-harness.  Env knobs: CONFIG9_SESSIONS / ROUNDS / CHANGES /
    DOC_LEN / SHARDS / BATCH / TICKS; PERITEXT_ELASTIC_* tune the
    controller.
    """
    from peritext_tpu.bench.workloads import time_elastic_ab

    r = time_elastic_ab(
        sessions=int(os.environ.get("CONFIG9_SESSIONS", "32")),
        rounds=int(os.environ.get("CONFIG9_ROUNDS", "10")),
        changes_per_round=int(os.environ.get("CONFIG9_CHANGES", "4")),
        doc_len=int(os.environ.get("CONFIG9_DOC_LEN", "400")),
        shards=int(os.environ.get("CONFIG9_SHARDS", "4")),
        batch_target=int(os.environ.get("CONFIG9_BATCH", "16")),
        ticks_per_round=int(os.environ.get("CONFIG9_TICKS", "4")),
    )
    static, elastic = r["legs"]
    return {
        "config": 9,
        "workload": f"{r['sessions']}-session load spike on shard 0 of "
        f"{r['shards']}, {r['rounds']} rounds x {r['changes_per_round']} "
        f"changes/session, {r['doc_len']}-char docs",
        "byte_identity": r["byte_identity"],
        "recovered": r["recovered"],
        "static_late_p95_ms": round(static["late_p95_s"] * 1000, 1),
        "elastic_late_p95_ms": round(elastic["late_p95_s"] * 1000, 1),
        "elastic_early_p95_ms": round(elastic["early_p95_s"] * 1000, 1),
        "migrations": (elastic.get("controller") or {}).get("migrations", 0),
        "final_shard_sessions": elastic["shard_sessions"],
    }


def config10_doc_lifecycle() -> Dict[str, Any]:
    """Multi-tenant document lifecycle: a watermark-bounded device fleet
    serving a Zipf-skewed document population far larger than it can
    hold (runtime/lifecycle.py), vs a resident-only control on identical
    traffic.

    The record is the tenancy ratio (documents served / peak device rows
    held), the warm/cold admit-to-applied p95 split (cold = transparent
    hydrate-on-submit, its own SLO-able histogram), and the lifecycle
    protocol tallies — per-session byte-identity between the legs is
    asserted in-harness.  Env knobs: CONFIG10_SESSIONS / ROUNDS /
    CHANGES / DOC_LEN / SHARDS / WATERMARK; PERITEXT_LIFECYCLE_* tune
    the reaper when attached via env instead.
    """
    from peritext_tpu.bench.workloads import time_lifecycle_ab

    r = time_lifecycle_ab(
        sessions=int(os.environ.get("CONFIG10_SESSIONS", "32")),
        rounds=int(os.environ.get("CONFIG10_ROUNDS", "10")),
        changes_per_round=int(os.environ.get("CONFIG10_CHANGES", "16")),
        doc_len=int(os.environ.get("CONFIG10_DOC_LEN", "120")),
        shards=int(os.environ.get("CONFIG10_SHARDS", "2")),
        watermark=int(os.environ.get("CONFIG10_WATERMARK", "4")),
    )
    control, lifecycle = r["legs"]
    return {
        "config": 10,
        "workload": f"{r['sessions']} Zipf-accessed docs over a "
        f"{r['watermark']}-doc watermark, {r['shards']} shards, "
        f"{r['rounds']} rounds x {r['changes_per_round']} changes, "
        f"{r['doc_len']}-char docs",
        "byte_identity": r["byte_identity"],
        "ok": r["ok"],
        "tenancy_ratio": r["tenancy_ratio"],
        "control_peak_rows": control["peak_device_rows"],
        "lifecycle_peak_rows": lifecycle["peak_device_rows"],
        "warm_p95_ms": r["warm_p95_ms"],
        "cold_start_p95_ms": r["cold_start_p95_ms"],
        "cold_starts": lifecycle["cold_count"],
        "evictions": (lifecycle.get("lifecycle_stats") or {}).get("evictions", 0),
        "hydrations": (lifecycle.get("lifecycle_stats") or {}).get("hydrations", 0),
    }


CONFIGS = {
    1: config1_trace_replay,
    2: config2_fuzz_style,
    3: config3_batched_plain,
    4: config4_batched_marked,
    5: config5_multichip,
    6: config6_patched_fleet,
    7: config7_serving_plane,
    8: config8_sharded_serving,
    9: config9_elastic_serving,
    10: config10_doc_lifecycle,
}


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=int, choices=sorted(CONFIGS), required=True)
    parser.add_argument(
        "--platform",
        default="cpu",
        help="jax platform to pin before first backend use (default cpu — "
        "this image's TPU relay hangs at init when wedged, the same hazard "
        "bench.py guards with a supervised subprocess; pass 'ambient' to "
        "use whatever the environment provides, e.g. the real TPU)",
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=0,
        help="supervise the run in a child process and kill it after this "
        "many seconds (the bench.py pattern: we do the killing on our own "
        "schedule and label the result, instead of an external timeout(1) "
        "SIGTERM landing mid-TPU-execution); 0 runs in-process",
    )
    args = parser.parse_args()
    if args.timeout > 0:
        import subprocess
        import sys

        cmd = [
            sys.executable, "-m", "peritext_tpu.bench.configs",
            "--config", str(args.config), "--platform", args.platform,
        ]
        try:
            proc = subprocess.run(cmd, timeout=args.timeout)
        except subprocess.TimeoutExpired:
            print(json.dumps({"config": args.config, "timed_out": True,
                              "timeout_s": args.timeout}))
            raise SystemExit(1)
        raise SystemExit(proc.returncode)
    if args.platform != "ambient":
        import jax

        jax.config.update("jax_platforms", args.platform)
    record = CONFIGS[args.config]()
    if "conditions" not in record:
        from peritext_tpu.bench.conditions import measurement_conditions

        record["conditions"] = measurement_conditions()
    print(json.dumps(record))


if __name__ == "__main__":
    main()
