"""Measurement-conditions stamp for perf artifacts (VERDICT r4 weak #4).

On a one-core box every latency number is load-dependent: the same code
measured 6.6-19.8 ms p50 across round-4 artifacts depending on what else
was running.  The perf emitters (bench.py's headline line via run_bench,
the BASELINE configs CLI, scripts/stream_ab.py, SELFBENCH records) embed
this stamp so round-over-round comparisons can be read honestly.
"""
from __future__ import annotations

import os
import subprocess
import time
from typing import Any, Dict


def measurement_conditions(platform: str | None = None) -> Dict[str, Any]:
    """One JSON-able dict: platform, commit, load average, competing
    processes, CPU count, wall time.  Cheap enough to call per artifact."""
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        ).stdout.strip() or None
    except Exception:
        commit = None
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = -1.0
    # Competing compute: any R-state python/pytest besides ourselves is a
    # soak or bench stealing the core (nice 19 still steals ~35% here).
    # The comm filter also excludes the momentary `ps` child below, so an
    # idle box reads 0.
    competitors = 0
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,stat,comm"], capture_output=True, text=True, timeout=10
        ).stdout
        me = os.getpid()
        for line in out.splitlines()[1:]:
            parts = line.split(None, 2)
            if (
                len(parts) == 3
                and parts[1].startswith("R")
                and int(parts[0]) != me
                and ("python" in parts[2] or "pytest" in parts[2])
            ):
                competitors += 1
    except Exception:
        competitors = -1
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:
            platform = "unknown"
    return {
        "platform": platform,
        "commit": commit,
        "load_avg": [round(load1, 2), round(load5, 2), round(load15, 2)],
        "competing_running_procs": competitors,
        "cpu_count": os.cpu_count(),
        "unix_time": int(time.time()),
    }
