"""Declarative mark schema — the config table that drives mark semantics.

This is the equivalent of the reference's ProseMirror ``markSpec``
(/root/reference/src/schema.ts:45-96): a tiny static table consumed by the
formatting engine.  Two flags drive the whole algorithm:

- ``inclusive``: the mark's *end* grows to absorb text typed at its right
  boundary (bold/italic do; links and comments don't).  Consumed when anchoring
  mark endpoints (see :func:`peritext_tpu.oracle.doc.change_mark`, reference
  peritext.ts:466-467).
- ``allow_multiple``: overlapping same-type marks coexist as a set (comments)
  instead of resolving last-writer-wins (reference peritext.ts:304, schema.ts:77).

Because the table is static, the TPU engine bakes it into compiled kernels as
integer constants (`INCLUSIVE_BY_ID` / `ALLOW_MULTIPLE_BY_ID` arrays), so mark
semantics cost nothing at runtime.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Tuple


@dataclass(frozen=True)
class MarkSpec:
    """Configuration for one mark type (reference schema.ts:45-96)."""

    inclusive: bool
    allow_multiple: bool
    attr_keys: Tuple[str, ...] = ()


# The four mark types of the reference schema, in declaration order.
# Reference: schema.ts:46-95 and ALL_MARKS at schema.ts:125.
MARK_SPEC: Mapping[str, MarkSpec] = {
    "strong": MarkSpec(inclusive=True, allow_multiple=False),
    "em": MarkSpec(inclusive=True, allow_multiple=False),
    "comment": MarkSpec(inclusive=False, allow_multiple=True, attr_keys=("id",)),
    "link": MarkSpec(inclusive=False, allow_multiple=False, attr_keys=("url",)),
}

ALL_MARKS: Tuple[str, ...] = tuple(MARK_SPEC)

# Integer ids for mark types, used by the tensorized engine.
MARK_TYPE_ID = {name: i for i, name in enumerate(ALL_MARKS)}
NUM_MARK_TYPES = len(ALL_MARKS)

# Dense views of the schema flags, indexable by mark-type id inside kernels.
INCLUSIVE_BY_ID = tuple(MARK_SPEC[t].inclusive for t in ALL_MARKS)
ALLOW_MULTIPLE_BY_ID = tuple(MARK_SPEC[t].allow_multiple for t in ALL_MARKS)


def is_mark_type(s: str) -> bool:
    """Reference schema.ts:133-140 (isMarkType)."""
    return s in MARK_SPEC
