"""Declarative mark schema — the config table that drives mark semantics.

This is the equivalent of the reference's ProseMirror ``markSpec``
(/root/reference/src/schema.ts:45-96): a tiny static table consumed by the
formatting engine.  Two flags drive the whole algorithm:

- ``inclusive``: the mark's *end* grows to absorb text typed at its right
  boundary (bold/italic do; links and comments don't).  Consumed when anchoring
  mark endpoints (see :func:`peritext_tpu.oracle.doc.change_mark`, reference
  peritext.ts:466-467).
- ``allow_multiple``: overlapping same-type marks coexist as a set (comments)
  instead of resolving last-writer-wins (reference peritext.ts:304, schema.ts:77).

The table is extensible at runtime (:func:`register_mark_type`, the
reference's demoMarkSpec pattern).  The tensorized engine therefore consumes
the flags as a small fixed-size *input vector* built at call time
(:func:`allow_multiple_array`) — never as jit-captured constants, which would
go stale when a type registers after a kernel has been traced.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class MarkSpec:
    """Configuration for one mark type (reference schema.ts:45-96).

    ``excludes`` is the editor-facing exclusion group (reference
    schema.ts:77, the ProseMirror markSpec field): None means the editor
    default (a mark excludes its own type), "" excludes nothing (how
    comment permits same-type overlap at the editor layer), and a
    space-separated name list names an explicit group.  CRDT merge
    behavior reads ``allow_multiple`` — ``excludes`` configures the
    consuming editor's schema, exactly as in the reference where the CRDT
    reads allowMultiple (peritext.ts:304) and ProseMirror reads excludes.
    """

    inclusive: bool
    allow_multiple: bool
    attr_keys: Tuple[str, ...] = ()
    excludes: "str | None" = None


# The four mark types of the reference schema, in declaration order.
# Reference: schema.ts:46-95 and ALL_MARKS at schema.ts:125.
MARK_SPEC: "dict[str, MarkSpec]" = {
    "strong": MarkSpec(inclusive=True, allow_multiple=False),
    "em": MarkSpec(inclusive=True, allow_multiple=False),
    "comment": MarkSpec(
        inclusive=False, allow_multiple=True, attr_keys=("id",), excludes=""
    ),
    "link": MarkSpec(inclusive=False, allow_multiple=False, attr_keys=("url",)),
}

# Mutable registry views.  The tensorized engine consumes the flags as small
# device arrays built at call time (allow_multiple_array), so registered
# types take effect without recompiling anything but the shapes they change.
ALL_MARKS: Tuple[str, ...] = tuple(MARK_SPEC)
MARK_TYPE_ID = {name: i for i, name in enumerate(ALL_MARKS)}
NUM_MARK_TYPES = len(ALL_MARKS)
INCLUSIVE_BY_ID = tuple(MARK_SPEC[t].inclusive for t in ALL_MARKS)
ALLOW_MULTIPLE_BY_ID = tuple(MARK_SPEC[t].allow_multiple for t in ALL_MARKS)

# Kernel flag vectors are padded to a fixed capacity so registering a mark
# type never changes jitted shapes.
MAX_MARK_TYPES = 16


def _rebuild_views() -> None:
    global ALL_MARKS, NUM_MARK_TYPES, INCLUSIVE_BY_ID, ALLOW_MULTIPLE_BY_ID
    ALL_MARKS = tuple(MARK_SPEC)
    # MARK_TYPE_ID mutates in place so `from schema import MARK_TYPE_ID`
    # bindings elsewhere stay live; consumers of the tuple views must access
    # them as schema attributes (`schema.ALL_MARKS`).
    MARK_TYPE_ID.clear()
    MARK_TYPE_ID.update({name: i for i, name in enumerate(ALL_MARKS)})
    NUM_MARK_TYPES = len(ALL_MARKS)
    INCLUSIVE_BY_ID = tuple(MARK_SPEC[t].inclusive for t in ALL_MARKS)
    ALLOW_MULTIPLE_BY_ID = tuple(MARK_SPEC[t].allow_multiple for t in ALL_MARKS)


def register_mark_type(
    name: str,
    inclusive: bool,
    allow_multiple: bool = False,
    attr_keys: Tuple[str, ...] = (),
    excludes: "str | None" = None,
) -> None:
    """Extend the mark schema at runtime (the reference's demoMarkSpec
    pattern, schema.ts:99-121: demos add highlightChange/unhighlightChange
    on top of the core four).

    Idempotent for identical re-registration; conflicting redefinition of an
    existing type raises.  Register before creating the documents that use
    the type — mark-type ids are append-only, so existing docs stay valid.
    """
    spec = MarkSpec(
        inclusive=inclusive,
        allow_multiple=allow_multiple,
        attr_keys=tuple(attr_keys),
        excludes=excludes,
    )
    existing = MARK_SPEC.get(name)
    if existing is not None:
        if existing != spec:
            raise ValueError(f"mark type {name!r} already registered with different flags")
        return
    if len(MARK_SPEC) >= MAX_MARK_TYPES:
        raise ValueError(f"mark schema is full ({MAX_MARK_TYPES} types)")
    MARK_SPEC[name] = spec
    _rebuild_views()


def allow_multiple_array():
    """The allowMultiple flags as a fixed-size numpy vector for kernels."""
    import numpy as np

    out = np.zeros(MAX_MARK_TYPES, bool)
    out[: NUM_MARK_TYPES] = ALLOW_MULTIPLE_BY_ID
    return out


def is_mark_type(s: str) -> bool:
    """Reference schema.ts:133-140 (isMarkType)."""
    return s in MARK_SPEC
