"""peritext-tpu: a TPU-native rich-text CRDT framework.

Capabilities match inkandswitch/peritext (see SURVEY.md): a convergent CRDT
for collaboratively edited rich text with inline formatting, replication via
causally-gated change logs, incremental patch streams, stable cursors, trace
replay, fuzzing — plus a batched, jit-compiled merge engine that scales over
TPU device meshes.

Layers:
- ``peritext_tpu.oracle``  — exact scalar semantics (host front-end + oracle)
- ``peritext_tpu.ops``     — tensorized document state and jitted kernels
- ``peritext_tpu.parallel``— replica-batch sharding over device meshes
- ``peritext_tpu.runtime`` — replication plumbing (queues, pubsub, logs,
                              checkpointing)
"""
from peritext_tpu.oracle import Doc, accumulate_patches
from peritext_tpu.schema import MARK_SPEC, MARK_TYPE_ID, register_mark_type


def __getattr__(name):
    # ALL_MARKS is rebound when mark types register; forward dynamically so
    # `peritext_tpu.ALL_MARKS` is never stale.
    if name == "ALL_MARKS":
        from peritext_tpu import schema

        return schema.ALL_MARKS
    # Observability facade: `peritext_tpu.obs` IS the telemetry module
    # (enable/span/counter/snapshot/summary) — loaded lazily; telemetry
    # itself is dependency-free, but runtime-package import is deferred
    # for oracle-only users.
    if name == "obs":
        from peritext_tpu.runtime import telemetry

        return telemetry
    # Engine classes load lazily so oracle-only users never pay the jax
    # import.
    if name in ("TpuDoc", "TpuUniverse"):
        from peritext_tpu import ops

        return getattr(ops, name)
    # Bridge surfaces load lazily for the same reason (Editor pulls in the
    # runtime package).
    if name in (
        "Editor",
        "EditorNetwork",
        "RemoteChangeHighlighter",
        "editor_doc_from_spans",
        "editor_doc_text",
        "content_pos_from_editor_pos",
        "initialize_docs",
    ):
        from peritext_tpu import bridge

        return getattr(bridge, name)
    raise AttributeError(name)

__version__ = "0.1.0"

__all__ = [
    "Doc",
    "accumulate_patches",
    "ALL_MARKS",
    "register_mark_type",
    "MARK_SPEC",
    "MARK_TYPE_ID",
    "TpuDoc",
    "TpuUniverse",
    "Editor",
    "EditorNetwork",
    "RemoteChangeHighlighter",
    "editor_doc_from_spans",
    "editor_doc_text",
    "content_pos_from_editor_pos",
    "initialize_docs",
    "obs",
    "__version__",
]
