"""Exact-semantics rich-text CRDT document (scalar reference engine).

Re-expresses the semantics of the reference implementation:

- list CRDT (RGA with tombstones):  /root/reference/src/micromerge.ts
- rich-text mark engine:            /root/reference/src/peritext.ts

This is *not* a port of the reference's class structure; it is a from-scratch
Python engine that reproduces the observable semantics: the
``InputOperation`` -> ``Change``/``Patch`` contract, the wire format, and the
flattened ``FormatSpanWithText`` output.  Every behavior is cited back to the
reference file:line it must agree with, because this module is the oracle the
TPU kernels are differential-tested against.

Representation choices (deliberately different from the reference):

- Operation ids stay ``"ctr@actor"`` strings at this layer (wire compatible),
  but mark-operation *sets* are sets of op-id strings plus a doc-level op
  table (``self.mark_ops``), instead of sets of object references.  Set
  membership by op id is equivalent: the reference only ever inserts each
  freshly-created op object once per set (peritext.ts:238-244).
- ``ROOT`` is represented as ``None`` (the reference uses a JS Symbol which
  serializes to an *absent* ``obj`` key in trace JSON; we mirror that in
  :func:`op_to_wire` / :func:`op_from_wire`).
- ``opsToMarks`` iterates ops in ascending (counter, actor) order, so
  last-writer-wins falls out of overwrite order. The reference iterates in
  set-insertion order with explicit op-id comparisons (peritext.ts:294-326);
  both compute the same map for ``allowMultiple == false`` marks.  For
  ``allowMultiple`` marks (comments) the reference's result is
  insertion-order dependent when adds and removes of the same comment id
  race; we resolve each comment id by op-id LWW, which is deterministic and
  agrees with the reference on every behavior its tests/fuzzer exercise
  (the reference fuzzer never issues comment removals — its
  ``removeMarkChange`` builds an ``addMark`` op, fuzz.ts:78-84).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from peritext_tpu.ids import compare_op_ids, make_op_id, op_sort_key
from peritext_tpu.schema import MARK_SPEC

# Sentinels.  ROOT is the document root object id; HEAD is the "insert at
# start of list" reference element (micromerge.ts:17-19).  Both serialize as
# an absent key on the wire.
ROOT = None
HEAD = None

# Patches hardcode the text path (reference micromerge.ts:643,592 emit
# ``path: ["text"]`` for every list patch regardless of the actual object).
CONTENT_KEY = "text"

Json = Any
MarkMap = Dict[str, Any]
Patch = Dict[str, Any]
Change = Dict[str, Any]
Operation = Dict[str, Any]


class ListItem:
    """Metadata for one RGA element (reference ListItemMetadata, micromerge.ts:237-253).

    ``mark_ops_before`` / ``mark_ops_after`` are ``None`` (no boundary here —
    formatting inherits from the left) or a set of mark-op ids (an explicit
    boundary; may be empty, which clears formatting).  The None/empty
    distinction is load-bearing: see peritext.ts:183 (``||`` on undefined)
    and peritext.ts:372-376.
    """

    __slots__ = ("elem_id", "value_id", "deleted", "mark_ops_before", "mark_ops_after")

    def __init__(self, elem_id: str, value_id: str, deleted: bool = False):
        self.elem_id = elem_id
        self.value_id = value_id
        self.deleted = deleted
        self.mark_ops_before: Optional[Set[str]] = None
        self.mark_ops_after: Optional[Set[str]] = None

    def get_side(self, side: str) -> Optional[Set[str]]:
        return self.mark_ops_before if side == "before" else self.mark_ops_after

    def set_side(self, side: str, ops: Set[str]) -> None:
        if side == "before":
            self.mark_ops_before = ops
        else:
            self.mark_ops_after = ops


class MapMeta:
    """CRDT metadata for a map object (reference MapMetadata, micromerge.ts:217-234)."""

    __slots__ = ("key_ops", "children")

    def __init__(self) -> None:
        self.key_ops: Dict[str, str] = {}  # key -> opId that last set it
        self.children: Dict[str, Optional[str]] = {}  # key -> child object id


# ---------------------------------------------------------------------------
# Mark resolution (reference peritext.ts:294-330)
# ---------------------------------------------------------------------------


def ops_to_marks(op_ids: Set[str], mark_ops: Dict[str, Operation]) -> MarkMap:
    """Resolve a set of mark ops into an effective mark map.

    Reference peritext.ts:294-326 (opsToMarks).  Non-``allowMultiple`` marks
    resolve last-writer-wins by op id; ``allowMultiple`` marks (comments)
    keep an id-sorted list of attrs.  Iterating in ascending op-id order and
    overwriting makes LWW fall out naturally and is order-deterministic.
    """
    mark_map: MarkMap = {}
    comment_state: Dict[str, Dict[str, Tuple[bool, Dict[str, Any]]]] = {}
    for op_id in sorted(op_ids, key=op_sort_key):
        op = mark_ops[op_id]
        mark_type = op["markType"]
        if not MARK_SPEC[mark_type].allow_multiple:
            if op["action"] == "addMark":
                attrs = op.get("attrs")
                mark_map[mark_type] = dict(attrs) if attrs else {"active": True}
            else:
                mark_map.pop(mark_type, None)
        else:
            per_id = comment_state.setdefault(mark_type, {})
            attrs = dict(op.get("attrs") or {})
            per_id[attrs.get("id")] = (op["action"] == "addMark", attrs)
    for mark_type, per_id in comment_state.items():
        values = [attrs for (_id, (active, attrs)) in sorted(per_id.items(), key=lambda kv: kv[0]) if active]
        if values:
            mark_map[mark_type] = values
        elif mark_type in mark_map:  # pragma: no cover - defensive
            del mark_map[mark_type]
    return mark_map


def find_closest_mark_ops_to_left(
    metadata: List[ListItem], index: int, side: str
) -> Set[str]:
    """Nearest explicit boundary set at or left of (index, side), exclusive.

    Reference peritext.ts:405-436 (findClosestMarkOpsToLeft).  Returns a
    fresh set (never a shared reference).
    """
    if side == "after" and metadata[index].mark_ops_before is not None:
        return set(metadata[index].mark_ops_before)
    for i in range(index - 1, -1, -1):
        after = metadata[i].mark_ops_after
        if after is not None:
            return set(after)
        before = metadata[i].mark_ops_before
        if before is not None:
            return set(before)
    return set()


def get_active_marks_at_index(
    metadata: List[ListItem], index: int, mark_ops: Dict[str, Operation]
) -> MarkMap:
    """Marks inherited by an insertion at metadata position ``index``.

    Reference peritext.ts:328-330.
    """
    return ops_to_marks(find_closest_mark_ops_to_left(metadata, index, "before"), mark_ops)


# ---------------------------------------------------------------------------
# Flattening (reference peritext.ts:337-395, 438-455)
# ---------------------------------------------------------------------------


def add_characters_to_spans(
    characters: List[str], marks: MarkMap, spans: List[Dict[str, Any]]
) -> None:
    """Append chars to the span list, coalescing equal-mark runs.

    Reference peritext.ts:438-455 (addCharactersToSpans).
    """
    if not characters:
        return
    if spans and spans[-1]["marks"] == marks:
        spans[-1]["text"] += "".join(characters)
    else:
        spans.append({"marks": marks, "text": "".join(characters)})


def get_text_with_formatting(
    text: Sequence[str], metadata: List[ListItem], mark_ops: Dict[str, Operation]
) -> List[Dict[str, Any]]:
    """Batch codepath: materialize the document as formatted spans.

    Reference peritext.ts:337-395 (getTextWithFormatting).  Marks inherit
    left-to-right until the next explicit boundary; the "before" set of a
    character takes precedence over the previous character's "after" set.
    """
    spans: List[Dict[str, Any]] = []
    characters: List[str] = []
    marks: MarkMap = {}
    visible = 0
    for index, item in enumerate(metadata):
        new_marks: Optional[MarkMap] = None
        if item.mark_ops_before is not None:
            new_marks = ops_to_marks(item.mark_ops_before, mark_ops)
        elif index > 0 and metadata[index - 1].mark_ops_after is not None:
            new_marks = ops_to_marks(metadata[index - 1].mark_ops_after, mark_ops)
        if new_marks is not None:
            add_characters_to_spans(characters, marks, spans)
            characters = []
            marks = new_marks
        if not item.deleted:
            characters.append(text[visible])
            visible += 1
    add_characters_to_spans(characters, marks, spans)
    return spans


# ---------------------------------------------------------------------------
# Index <-> element id resolution (reference micromerge.ts:731-805)
# ---------------------------------------------------------------------------


def get_list_element_id(
    metadata: List[ListItem], index: int, look_after_tombstones: bool = False
) -> str:
    """Element id of the ``index``-th visible element.

    Reference micromerge.ts:762-805 (getListElementId).  With
    ``look_after_tombstones``, peeks past trailing tombstones that carry a
    ``markOpsAfter`` boundary so that new characters land *after* a span-end
    anchored on a tombstone (the non-growing-mark rule; motivating test:
    "handles growth behavior for spans where the boundary is a tombstone",
    reference test/micromerge.ts:520-566).
    """
    visible = -1
    for meta_index, item in enumerate(metadata):
        if item.deleted:
            continue
        visible += 1
        if visible != index:
            continue
        if look_after_tombstones:
            elem_index = meta_index
            peek = meta_index + 1
            latest_after_tombstone: Optional[int] = None
            while peek < len(metadata) and metadata[peek].deleted:
                if metadata[peek].mark_ops_after is not None:
                    latest_after_tombstone = peek
                peek += 1
            if latest_after_tombstone:  # faithful: falsy 0 not taken (micromerge.ts:794)
                elem_index = latest_after_tombstone
            return metadata[elem_index].elem_id
        return item.elem_id
    raise IndexError(f"List index out of bounds: {index}")


# ---------------------------------------------------------------------------
# Mark op generation + application (reference peritext.ts:154-281, 458-501)
# ---------------------------------------------------------------------------


def change_mark(
    input_op: Dict[str, Any],
    obj_id: Optional[str],
    metadata: List[ListItem],
    obj: List[str],
) -> Operation:
    """Translate an addMark/removeMark input op into an anchored internal op.

    Reference peritext.ts:458-501 (changeMark).  Start never grows
    (``startGrows`` hardcoded false, peritext.ts:466); the end grows iff the
    mark type is ``inclusive`` (peritext.ts:467).  A growing end anchors on
    the *next* character's "before" slot (or endOfText); a non-growing end
    anchors on the last covered character's "after" slot.
    """
    start_index = input_op["startIndex"]
    end_index = input_op["endIndex"]
    end_grows = MARK_SPEC[input_op["markType"]].inclusive

    start = {"type": "before", "elemId": get_list_element_id(metadata, start_index)}

    if end_grows and end_index >= len(obj):
        end: Dict[str, Any] = {"type": "endOfText"}
    elif end_grows:
        end = {"type": "before", "elemId": get_list_element_id(metadata, end_index)}
    else:
        end = {"type": "after", "elemId": get_list_element_id(metadata, end_index - 1)}

    op: Operation = {
        "action": input_op["action"],
        "obj": obj_id,
        "start": start,
        "end": end,
        "markType": input_op["markType"],
    }
    if input_op.get("attrs"):
        op["attrs"] = dict(input_op["attrs"])
    return op


def _boundary_matches(boundary: Dict[str, Any], side: str, elem_id: str) -> bool:
    return boundary["type"] == side and boundary.get("elemId") == elem_id


def apply_add_remove_mark(
    op: Operation,
    text: List[str],
    metadata: List[ListItem],
    mark_ops: Dict[str, Operation],
) -> List[Patch]:
    """The mark merge kernel: write the op into boundary sets, emit patches.

    Reference peritext.ts:154-223 (applyAddRemoveMark) plus its helpers
    calculateOpsForPosition (225-249), beginPartialPatch (251-267) and
    finishPartialPatch (269-281).  Walks the 2n boundary slots left-to-right
    with a BEFORE/DURING/AFTER state machine, carrying the inherited op set.

    Key subtlety preserved from the reference: the carried ``current_ops``
    is *not* updated with the op being applied — writes store
    ``current ∪ {op}`` (or ``∖`` at the end slot) but the carry keeps the old
    value (peritext.ts:181-186), so the end-slot write materializes the *old*
    inherited set.
    """
    patches: List[Patch] = []
    visible_index = 0
    current_ops: Set[str] = set()
    op_state = "BEFORE"
    partial_patch: Optional[Dict[str, Any]] = None
    obj_length = len(text)
    op_id = op["opId"]

    def finish_partial(partial: Dict[str, Any], end_index: int) -> None:
        # Reference finishPartialPatch: drop zero-width patches and patches
        # entirely beyond the visible text (peritext.ts:269-281).
        if end_index > partial["startIndex"] and partial["startIndex"] < obj_length:
            patch = dict(partial)
            patch["endIndex"] = min(end_index, obj_length)
            patches.append(patch)

    def begin_partial(visible: int) -> Dict[str, Any]:
        partial = {
            "action": op["action"],
            "markType": op["markType"],
            "path": ["text"],
            "startIndex": visible,
        }
        if op["action"] == "addMark" and op["markType"] in ("link", "comment"):
            partial["attrs"] = dict(op["attrs"])
        return partial

    done = False
    for item in metadata:
        for side in ("before", "after"):
            stored = item.get_side(side)
            if stored is not None:
                current_ops = stored

            # calculateOpsForPosition (peritext.ts:225-249)
            changed: Optional[Set[str]] = None
            if _boundary_matches(op["start"], side, item.elem_id):
                op_state = "DURING"
                changed = current_ops | {op_id}
            elif _boundary_matches(op["end"], side, item.elem_id):
                op_state = "AFTER"
                changed = current_ops - {op_id}
            elif op_state == "DURING" and stored is not None:
                changed = current_ops | {op_id}

            if changed is not None:
                item.set_side(side, changed)

            if side == "after" and not item.deleted:
                visible_index += 1

            if changed is not None:
                if partial_patch is not None:
                    finish_partial(partial_patch, visible_index)
                    partial_patch = None
                if op_state == "DURING" and ops_to_marks(current_ops, mark_ops) != ops_to_marks(
                    changed, mark_ops
                ):
                    partial_patch = begin_partial(visible_index)

            if op_state == "AFTER":
                done = True
                break
        if done:
            break

    if partial_patch is not None:
        finish_partial(partial_patch, visible_index)

    return patches


# ---------------------------------------------------------------------------
# Wire format (reference micromerge.ts:60-71 and traces/*.json)
# ---------------------------------------------------------------------------


def op_to_wire(op: Operation) -> Dict[str, Any]:
    """JSON-representation of an internal op, matching the reference traces.

    ``ROOT`` obj and ``HEAD`` elemId are JS Symbols in the reference and
    vanish under JSON.stringify, so we omit those keys.
    """
    return {k: v for k, v in op.items() if not (k in ("obj", "elemId") and v is None)}


def op_from_wire(op: Dict[str, Any]) -> Operation:
    op = dict(op)
    op.setdefault("obj", None)
    if op.get("insert") and "elemId" not in op:
        op["elemId"] = None
    return op


# ---------------------------------------------------------------------------
# The object graph (reference micromerge.ts:534-608's per-object dispatch)
# ---------------------------------------------------------------------------


class ObjectStore:
    """The CRDT object graph of one replica: objects + metadata keyed by
    creating op id, plus the doc-global mark-op table.

    Extracted from :class:`Doc` so the device engine can host the *same*
    semantics for its structural plane: every object except the
    device-resident text list (maps, nested lists, comment tables) applies
    ops through this store, exactly as the reference dispatches per object
    (micromerge.ts:534-608).  ``device_objects`` registers object ids whose
    list state lives elsewhere (the TPU DocState); routing an op for one of
    those here is a caller bug and raises.
    """

    def __init__(self) -> None:
        self.objects: Dict[Optional[str], Any] = {ROOT: {}}
        self.metadata: Dict[Optional[str], Any] = {ROOT: MapMeta()}
        self.mark_ops: Dict[str, Operation] = {}
        self.device_objects: Set[str] = set()

    # -- path resolution (reference micromerge.ts:446-463) ------------------

    def get_object_id_for_path(self, path: Sequence[str]) -> Optional[str]:
        object_id: Optional[str] = ROOT
        for path_elem in path:
            meta = self.metadata.get(object_id)
            if meta is None:
                raise KeyError(f"No object at path {path!r}")
            if isinstance(meta, list):
                raise KeyError(f"Object {path_elem} in path {path!r} is a list")
            child = meta.children.get(path_elem)
            if child is None:
                raise KeyError(f"Child not found: {path_elem}")
            object_id = child
        return object_id

    # -- op dispatch (reference micromerge.ts:534-608) ----------------------

    def apply_op(self, op: Operation) -> List[Patch]:
        obj_id = op.get("obj", None)
        if obj_id is not None and obj_id in self.device_objects:
            raise ValueError(
                f"op {op.get('opId')!r} targets device-resident object "
                f"{obj_id!r}; its list ops must route through the device "
                f"engine, not the host store"
            )
        metadata = self.metadata.get(obj_id, None)
        obj = self.objects.get(obj_id, None)
        if metadata is None or obj is None:
            raise KeyError(f"Object does not exist: {obj_id}")

        action = op["action"]
        if action == "makeMap":
            self.objects[op["opId"]] = {}
            self.metadata[op["opId"]] = MapMeta()
        elif action == "makeList":
            self.objects[op["opId"]] = []
            self.metadata[op["opId"]] = []

        if isinstance(metadata, list):
            if action == "set":
                if "elemId" not in op:
                    raise ValueError("Must specify elemId when calling set on an array")
                return self.apply_list_insert(op)
            if action == "del":
                if "elemId" not in op:
                    raise ValueError("Must specify elemId when calling del on an array")
                return self.apply_list_update(op)
            if action in ("addMark", "removeMark"):
                self.mark_ops[op["opId"]] = op
                return apply_add_remove_mark(op, obj, metadata, self.mark_ops)
            raise NotImplementedError(f"{action} on a list")

        # Map object: last-writer-wins by op id (micromerge.ts:578-602).
        key = op.get("key")
        if key is None:
            raise ValueError("Must specify key when calling set or del on a map")
        key_meta = metadata.key_ops.get(key)
        if key_meta is None or compare_op_ids(key_meta, op["opId"]) == -1:
            metadata.key_ops[key] = op["opId"]
            if action == "del":
                obj.pop(key, None)
            elif action == "makeList":
                obj[key] = self.objects[op["opId"]]
                metadata.children[key] = op["opId"]
                # Reference emits a makeList patch with hardcoded path
                # (micromerge.ts:592).
                return [{**op_to_wire(op), "path": ["text"]}]
            elif action == "makeMap":
                # Reference has a known bug here: no patch emitted
                # (micromerge.ts:594).  We are faithful to it.
                obj[key] = self.objects[op["opId"]]
                metadata.children[key] = op["opId"]
            elif action == "set":
                obj[key] = op["value"]
            else:
                raise NotImplementedError(action)
        return []

    # -- RGA insert (reference micromerge.ts:614-672) -----------------------

    def apply_list_insert(self, op: Operation) -> List[Patch]:
        metadata: List[ListItem] = self.metadata[op["obj"]]
        obj: List[str] = self.objects[op["obj"]]

        # Find the reference element; insert after it.
        if op.get("elemId") is None:
            index, visible = -1, 0
        else:
            index, visible = self.find_list_element(op["obj"], op["elemId"])
        if index >= 0 and not metadata[index].deleted:
            visible += 1
        index += 1

        # Convergence rule for concurrent same-position inserts: skip right
        # past any elements with elemId greater than this op's id
        # (micromerge.ts:630-635).
        op_id = op["opId"]
        while index < len(metadata) and compare_op_ids(op_id, metadata[index].elem_id) < 0:
            if not metadata[index].deleted:
                visible += 1
            index += 1

        metadata.insert(index, ListItem(elem_id=op_id, value_id=op_id))
        value = op["value"]
        if not isinstance(value, str):
            raise TypeError("Expected value inserted into text to be a string")
        obj.insert(visible, value)

        marks = get_active_marks_at_index(metadata, index, self.mark_ops)
        return [
            {
                "path": [CONTENT_KEY],
                "action": "insert",
                "index": visible,
                "values": [value],
                "marks": marks,
            }
        ]

    # -- delete (reference micromerge.ts:677-724) ---------------------------

    def apply_list_update(self, op: Operation) -> List[Patch]:
        index, visible = self.find_list_element(op["obj"], op["elemId"])
        metadata: List[ListItem] = self.metadata[op["obj"]]
        item = metadata[index]
        if op["action"] == "del":
            if not item.deleted:
                item.deleted = True
                self.objects[op["obj"]].pop(visible)
                return [
                    {
                        "path": [CONTENT_KEY],
                        "action": "delete",
                        "index": visible,
                        "count": 1,
                    }
                ]
        return []

    def find_list_element(
        self, object_id: Optional[str], elem_id: str
    ) -> Tuple[int, int]:
        """Reference micromerge.ts:731-755 (findListElement)."""
        meta = self.metadata.get(object_id)
        if not isinstance(meta, list):
            raise TypeError("Expected array metadata for find_list_element")
        visible = 0
        for index, item in enumerate(meta):
            if item.elem_id == elem_id:
                return index, visible
            if not item.deleted:
                visible += 1
        raise KeyError(f"List element not found: {elem_id}")

    def is_linked(self, obj_id: Optional[str], key: str) -> bool:
        """True while ``key`` in map ``obj_id`` still holds its bound child
        object.  ``children`` entries outlive del/LWW-overwrite (the
        reference never prunes them, micromerge.ts:592-600), so every view
        that materializes a child through ``children`` must gate on the
        *live* map value — this is that single shared predicate (used by
        the snapshot serializer below and TpuDoc.root)."""
        meta = self.metadata.get(obj_id)
        if not isinstance(meta, MapMeta):
            return False
        cid = meta.children.get(key)
        if cid is None:
            return False
        obj = self.objects.get(obj_id)
        return (
            isinstance(obj, dict)
            and key in obj
            and obj[key] is self.objects.get(cid)
        )

    # -- snapshot serialization (runtime/checkpoint.py sidecars) ------------

    def to_json(self) -> Dict[str, Any]:
        """JSON-serializable snapshot of the object graph.

        ROOT (None) keys map to ""; child-object values inside maps are
        re-linked from ``children`` on load rather than serialized inline.
        """
        objects: Dict[str, Any] = {}
        for obj_id, meta in self.metadata.items():
            key = "" if obj_id is None else obj_id
            if isinstance(meta, list):
                objects[key] = {
                    "type": "list",
                    "values": list(self.objects[obj_id]),
                    "items": [
                        [
                            it.elem_id,
                            it.value_id,
                            it.deleted,
                            sorted(it.mark_ops_before)
                            if it.mark_ops_before is not None
                            else None,
                            sorted(it.mark_ops_after)
                            if it.mark_ops_after is not None
                            else None,
                        ]
                        for it in meta
                    ],
                }
            else:
                obj = self.objects[obj_id]
                # ``children`` entries outlive del/LWW-overwrite (the
                # reference never prunes them, micromerge.ts:592-600), so
                # record which keys *currently* hold their child object —
                # only those re-link on load; a deleted key must not
                # resurrect and an overwritten one keeps its plain value.
                linked = sorted(
                    k for k in meta.children if self.is_linked(obj_id, k)
                )
                objects[key] = {
                    "type": "map",
                    "values": {k: v for k, v in obj.items() if k not in linked},
                    "key_ops": dict(meta.key_ops),
                    "children": dict(meta.children),
                    "linked": linked,
                }
        return {
            "objects": objects,
            "mark_ops": {k: dict(v) for k, v in self.mark_ops.items()},
            "device_objects": sorted(self.device_objects),
        }

    @classmethod
    def from_json(cls, data: Dict[str, Any]) -> "ObjectStore":
        store = cls()
        store.objects.clear()
        store.metadata.clear()
        # Pass 1: create every object and its metadata.
        for key, entry in data["objects"].items():
            obj_id = None if key == "" else key
            if entry["type"] == "list":
                meta: Any = []
                for elem_id, value_id, deleted, before, after in entry["items"]:
                    item = ListItem(elem_id=elem_id, value_id=value_id, deleted=deleted)
                    item.mark_ops_before = set(before) if before is not None else None
                    item.mark_ops_after = set(after) if after is not None else None
                    meta.append(item)
                store.objects[obj_id] = list(entry["values"])
                store.metadata[obj_id] = meta
            else:
                m = MapMeta()
                m.key_ops = dict(entry["key_ops"])
                m.children = dict(entry["children"])
                store.objects[obj_id] = dict(entry["values"])
                store.metadata[obj_id] = m
        # Pass 2: re-link child-object references inside map values — only
        # the keys that actually held their child at save time ("linked");
        # stale ``children`` entries (deleted or LWW-overwritten keys) must
        # not resurrect objects into the map.
        for key, entry in data["objects"].items():
            if entry["type"] == "map":
                obj_id = None if key == "" else key
                for child_key in entry["linked"]:
                    child_id = entry["children"][child_key]
                    if child_id in store.objects:
                        store.objects[obj_id][child_key] = store.objects[child_id]
        store.mark_ops = {k: dict(v) for k, v in data["mark_ops"].items()}
        store.device_objects = set(data.get("device_objects", ()))
        return store


# ---------------------------------------------------------------------------
# Local change generation against a store (reference micromerge.ts:308-441)
# ---------------------------------------------------------------------------


def generate_input_op(
    store: ObjectStore,
    input_op: Dict[str, Any],
    make_new_op,
) -> List[Patch]:
    """Expand one InputOperation into internal ops against ``store``.

    The body of the reference's change() loop (micromerge.ts:326-441),
    parameterized over ``make_new_op(op) -> (op_id, patches)`` — the
    caller allocates the op id, applies the op to its own state, and
    records the wire form.  Shared by :meth:`Doc.change` and the device
    engine's host-side generation path (TpuDoc), so the two can never
    diverge on generation semantics.
    """
    obj_id = store.get_object_id_for_path(input_op["path"])
    obj = store.objects.get(obj_id)
    meta = store.metadata.get(obj_id)
    if obj is None or meta is None:
        raise KeyError(f"Object doesn't exist: {obj_id}")
    action = input_op["action"]
    patches: List[Patch] = []

    if isinstance(obj, list) and isinstance(meta, list):
        if action == "insert":
            # One input op expands to one internal op per character,
            # chained so each op references the previous
            # (micromerge.ts:347-361).  The initial reference element
            # uses the tombstone-peek rule.
            elem_id = (
                HEAD
                if input_op["index"] == 0
                else get_list_element_id(
                    meta, input_op["index"] - 1, look_after_tombstones=True
                )
            )
            for value in input_op["values"]:
                elem_id, new_patches = make_new_op(
                    {
                        "action": "set",
                        "obj": obj_id,
                        "elemId": elem_id,
                        "insert": True,
                        "value": value,
                    }
                )
                patches.extend(new_patches)
        elif action == "delete":
            # Constant-index repeated deletion (micromerge.ts:362-392).
            for _ in range(input_op["count"]):
                elem_id = get_list_element_id(meta, input_op["index"])
                _, new_patches = make_new_op(
                    {"action": "del", "obj": obj_id, "elemId": elem_id}
                )
                patches.extend(new_patches)
        elif action in ("addMark", "removeMark"):
            partial_op = change_mark(input_op, obj_id, meta, obj)
            _, new_patches = make_new_op(partial_op)
            patches.extend(new_patches)
        elif action == "del":
            raise ValueError("Use the delete action for lists")
        else:
            raise NotImplementedError(f"{action} on a list")
    else:
        if action in ("makeList", "makeMap", "del"):
            _, new_patches = make_new_op(
                {"action": action, "obj": obj_id, "key": input_op["key"]}
            )
            patches.extend(new_patches)
        elif action == "set":
            _, new_patches = make_new_op(
                {
                    "action": "set",
                    "obj": obj_id,
                    "key": input_op["key"],
                    "value": input_op["value"],
                }
            )
            patches.extend(new_patches)
        else:
            raise TypeError(f"Not a list: {input_op['path']}")
    return patches


# ---------------------------------------------------------------------------
# The document
# ---------------------------------------------------------------------------


class Doc:
    """A collaborative rich-text document replica (exact semantics).

    Equivalent surface to the reference ``Micromerge`` class
    (micromerge.ts:262-756): ``change()`` generates a :data:`Change` from
    input operations and applies it locally; ``apply_change()`` ingests a
    remote change behind a causal-readiness gate; ``get_text_with_formatting``
    materializes formatted spans; cursors resolve through tombstones.
    """

    CONTENT_KEY = CONTENT_KEY

    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self.seq = 0
        self.max_op = 0
        self.clock: Dict[str, int] = {}
        # The object graph (objects/metadata keyed by creating op id,
        # ROOT is None) plus the doc-global mark-op table.
        self.store = ObjectStore()

    # -- store views (kept as attributes for the differential tests) --------

    @property
    def objects(self) -> Dict[Optional[str], Any]:
        return self.store.objects

    @property
    def metadata(self) -> Dict[Optional[str], Any]:
        return self.store.metadata

    @property
    def mark_ops(self) -> Dict[str, Operation]:
        return self.store.mark_ops

    # -- public accessors ---------------------------------------------------

    @property
    def root(self) -> Dict[str, Any]:
        return self.objects[ROOT]

    def get_object_id_for_path(self, path: Sequence[str]) -> Optional[str]:
        """Reference micromerge.ts:446-463 (getObjectIdForPath)."""
        return self.store.get_object_id_for_path(path)

    def get_text_with_formatting(self, path: Sequence[str]) -> List[Dict[str, Any]]:
        """Reference micromerge.ts:516-529."""
        object_id = self.get_object_id_for_path(path)
        text = self.objects.get(object_id)
        metadata = self.metadata.get(object_id)
        if not isinstance(text, list) or not isinstance(metadata, list):
            raise TypeError(f"Expected a list at object ID {object_id}")
        return get_text_with_formatting(text, metadata, self.mark_ops)

    # -- cursors (reference micromerge.ts:465-477) --------------------------

    def get_cursor(self, path: Sequence[str], index: int) -> Dict[str, Any]:
        object_id = self.get_object_id_for_path(path)
        return {
            "objectId": object_id,
            "elemId": get_list_element_id(self.metadata[object_id], index),
        }

    def resolve_cursor(self, cursor: Dict[str, Any]) -> int:
        _, visible = self._find_list_element(cursor["objectId"], cursor["elemId"])
        return visible

    # -- local change generation (reference micromerge.ts:308-441) ----------

    def change(self, input_ops: Sequence[Dict[str, Any]]) -> Tuple[Change, List[Patch]]:
        deps = dict(self.clock)
        # Resume from our own clock entry: a replica rebuilt by replaying a
        # log containing its own past changes (the durability model, SURVEY
        # §5) must not re-issue already-used sequence numbers — peers would
        # silently drop the colliding change.  In every reference-exercised
        # flow self.seq already equals clock[actor], so this is a no-op
        # there (micromerge.ts:318 bumps seq unconditionally).
        self.seq = max(self.seq, self.clock.get(self.actor_id, 0)) + 1
        self.clock[self.actor_id] = self.seq

        change: Change = {
            "actor": self.actor_id,
            "seq": self.seq,
            "deps": deps,
            "startOp": self.max_op + 1,
            "ops": [],
        }
        patches: List[Patch] = []
        for input_op in input_ops:
            patches.extend(
                generate_input_op(
                    self.store, input_op, lambda op: self._make_new_op(change, op)
                )
            )
        return change, patches

    def _make_new_op(
        self, change: Change, op: Operation
    ) -> Tuple[str, List[Patch]]:
        """Reference micromerge.ts:483-493 (makeNewOp)."""
        self.max_op += 1
        op_id = make_op_id(self.max_op, self.actor_id)
        op_with_id = {"opId": op_id, **op}
        patches = self._apply_op(op_with_id)
        # Changes carry wire-format ops (absent obj/elemId keys stand in for
        # the reference's ROOT/HEAD Symbols, which vanish under
        # JSON.stringify) so a change JSON-serializes byte-compatibly.
        change["ops"].append(op_to_wire(op_with_id))
        return op_id, patches

    # -- remote ingestion (reference micromerge.ts:499-514) -----------------

    def apply_change(self, change: Change) -> List[Patch]:
        """Causal gate + op application.  Raises ``ValueError`` on causal gaps
        (the reference throws RangeError, micromerge.ts:501-509)."""
        last_seq = self.clock.get(change["actor"], 0)
        if change["seq"] != last_seq + 1:
            raise ValueError(
                f"Expected sequence number {last_seq + 1}, got {change['seq']}"
            )
        for actor, dep in (change.get("deps") or {}).items():
            if self.clock.get(actor, 0) < dep:
                raise ValueError(f"Missing dependency: change {dep} by actor {actor}")
        self.clock[change["actor"]] = change["seq"]
        self.max_op = max(self.max_op, change["startOp"] + len(change["ops"]) - 1)

        patches: List[Patch] = []
        for op in change["ops"]:
            patches.extend(self._apply_op(op_from_wire(op)))
        return patches

    # -- op dispatch (reference micromerge.ts:534-608) ----------------------

    def _apply_op(self, op: Operation) -> List[Patch]:
        return self.store.apply_op(op)

    def _find_list_element(
        self, object_id: Optional[str], elem_id: str
    ) -> Tuple[int, int]:
        """Reference micromerge.ts:731-755 (findListElement)."""
        return self.store.find_list_element(object_id, elem_id)


# ---------------------------------------------------------------------------
# Patch-accumulation differential oracle (reference test/accumulatePatches.ts)
# ---------------------------------------------------------------------------


def accumulate_patches(patches: Sequence[Patch]) -> List[Dict[str, Any]]:
    """Naive per-character patch applier -> formatted spans.

    Faithful to reference test/accumulatePatches.ts:9-80, including its
    quirks (``removeMark`` deletes the whole mark entry regardless of type).
    Used to assert the incremental patch stream and the batch flatten agree.
    """
    chars: List[Dict[str, Any]] = []
    for patch in patches:
        if patch.get("path") != ["text"]:
            raise ValueError("accumulate_patches only supports the 'text' path")
        action = patch["action"]
        if action == "insert":
            for offset, character in enumerate(patch["values"]):
                chars.insert(
                    patch["index"] + offset,
                    {"character": character, "marks": dict(patch["marks"])},
                )
        elif action == "delete":
            del chars[patch["index"] : patch["index"] + patch["count"]]
        elif action == "addMark":
            for index in range(patch["startIndex"], patch["endIndex"]):
                marks = chars[index]["marks"]
                if patch["markType"] != "comment":
                    marks[patch["markType"]] = dict(patch.get("attrs") or {"active": True})
                else:
                    existing = marks.get("comment")
                    if existing is None:
                        marks["comment"] = [dict(patch["attrs"])]
                    elif not any(c["id"] == patch["attrs"]["id"] for c in existing):
                        marks["comment"] = sorted(
                            existing + [dict(patch["attrs"])], key=lambda c: c["id"]
                        )
        elif action == "removeMark":
            for index in range(patch["startIndex"], patch["endIndex"]):
                chars[index]["marks"].pop(patch["markType"], None)
        elif action == "makeList":
            pass
        else:
            raise ValueError(f"Unknown patch action: {action}")

    spans: List[Dict[str, Any]] = []
    for ch in chars:
        add_characters_to_spans([ch["character"]], ch["marks"], spans)
    return spans
