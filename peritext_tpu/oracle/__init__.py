"""Scalar exact-semantics document engine (the host/oracle layer).

This sub-package re-expresses the reference's CRDT semantics
(/root/reference/src/micromerge.ts + peritext.ts) as plain Python with no JAX
dependency.  It serves three roles in the framework:

1. **Semantic oracle** for differential testing of the TPU kernels (every
   tensorized codepath must agree with this one, byte for byte).
2. **Interactive front-end**: op generation for live editing sessions goes
   through this layer (the ``change()`` path); the batched TPU engine is the
   merge/replay data plane.
3. **Wire-format authority**: ``Change`` dicts produced here JSON-serialize to
   the reference's exact change format (micromerge.ts:60-71), so reference
   failure traces replay directly.
"""
from peritext_tpu.oracle.doc import (
    Doc,
    HEAD,
    ObjectStore,
    ROOT,
    accumulate_patches,
    add_characters_to_spans,
    get_list_element_id,
    get_text_with_formatting,
    ops_to_marks,
)

__all__ = [
    "Doc",
    "HEAD",
    "ObjectStore",
    "ROOT",
    "accumulate_patches",
    "add_characters_to_spans",
    "get_list_element_id",
    "get_text_with_formatting",
    "ops_to_marks",
]
