"""Generative convergence fuzzer.

Reference: test/fuzz.ts — N replicas, random ops, random pairwise
anti-entropy syncs, asserting after every sync that (a) the accumulated patch
stream equals the batch flatten on both replicas and (b) the pair converged
(equal clocks, equal spans).  Failures serialize a full reproducible state
(queues + syncs), which :func:`peritext_tpu.replay.replay_change_log` can
re-execute.

Differences from the reference fuzzer, on purpose:
- Seeded/deterministic (reference uses Math.random with no seed).
- Comment removeMark is generated *as a removeMark* with a known id.  (The
  reference's removeMarkChange constructs an addMark by mistake, fuzz.ts:78 —
  so comment removal was never actually fuzzed upstream.)  Comment-remove
  convergence holds under this engine's per-id LWW semantics.
- Also drives the engine under test via ``doc_factory`` so the same harness
  differential-tests the TPU engine against the oracle.
"""
from __future__ import annotations

import itertools
import json
import math
import os
import random
from typing import Any, Callable, Dict, List, Optional

from peritext_tpu.oracle import Doc, accumulate_patches
from peritext_tpu.runtime.faults import FaultPlan
from peritext_tpu.runtime.log import ChangeLog
from peritext_tpu.runtime.sync import apply_changes
from peritext_tpu.testing import generate_docs

MARK_TYPES = ["strong", "em", "link", "comment"]
EXAMPLE_URLS = [f"{c}.com" for c in "ABCDEFGHIJKLMNOPQRSTUVWXYZ"]

# Delivery chaos applied by --chaos between replicas: a quarter of messages
# dropped, a fifth duplicated, a quarter held back/reordered.  Convergence is
# asserted at fault-free quiesce points (every ``chaos_quiesce`` iterations),
# where full anti-entropy from the durable log must restore byte-identical
# replicas — the paper's claim under adversarial delivery.
DEFAULT_CHAOS_SPEC = "pubsub_deliver:drop=0.25,dup=0.2,reorder=0.25"


class FuzzError(AssertionError):
    def __init__(self, message: str, state: Dict[str, Any]):
        super().__init__(message)
        self.state = state

    def save(self, path: str) -> None:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.state, f)


def _text_len(doc: Any) -> int:
    """Visible length of the list at path ["text"] — robust to the root
    'text' key being LWW-overwritten with a plain value (the path still
    resolves through the unpruned ``children`` entry, micromerge.ts:592-600,
    so list ops keep targeting the original device list)."""
    t = doc.root.get("text")
    if isinstance(t, list):
        return len(t)
    return sum(len(s["text"]) for s in doc.get_text_with_formatting(["text"]))


def _random_add_mark(rng: random.Random, doc: Doc, comment_history: List[str]) -> Dict[str, Any]:
    length = _text_len(doc)
    start = rng.randrange(length)
    end = start + rng.randrange(length - start) + 1
    mark_type = rng.choice(MARK_TYPES)
    op: Dict[str, Any] = {
        "path": ["text"],
        "action": "addMark",
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "link":
        op["attrs"] = {"url": rng.choice(EXAMPLE_URLS)}
    elif mark_type == "comment":
        comment_id = f"comment-{rng.randrange(1 << 16):04x}"
        comment_history.append(comment_id)
        op["attrs"] = {"id": comment_id}
    return op


def _random_remove_mark(
    rng: random.Random, doc: Doc, comment_history: List[str], allow_comment_remove: bool
) -> Dict[str, Any]:
    length = _text_len(doc)
    start = rng.randrange(length)
    end = start + rng.randrange(length - start) + 1
    choices = [t for t in MARK_TYPES if allow_comment_remove or t != "comment"]
    mark_type = rng.choice(choices)
    op: Dict[str, Any] = {
        "path": ["text"],
        "action": "removeMark",
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "comment":
        if not comment_history:
            op["markType"] = "strong"
        else:
            op["attrs"] = {"id": rng.choice(comment_history)}
    return op


def _random_insert(rng: random.Random, doc: Doc, max_chars: int) -> Optional[Dict[str, Any]]:
    length = _text_len(doc)
    index = rng.randrange(length) if length else 0
    num = rng.randrange(max_chars)
    values = [rng.choice("0123456789abcdef") for _ in range(num)]
    return {"path": ["text"], "action": "insert", "index": index, "values": values}


def _random_delete(rng: random.Random, doc: Doc) -> Optional[Dict[str, Any]]:
    length = _text_len(doc)
    # Faithful to the reference's bounds (fuzz.ts:128-129), which never
    # delete the entire document (a noted real bug when you do).
    index = rng.randrange(length) + 1
    count = math.ceil(rng.random() * (length - index))
    if count <= 0:
        return None
    return {"path": ["text"], "action": "delete", "index": index, "count": count}


# -- growth-biased workload (VERDICT r4 weak #3) -----------------------------
#
# The reference-shaped ops above keep fuzz docs at 1-6 chars forever (the
# delete takes a random fraction of the WHOLE tail), so the chunk valves,
# PATCH_GROUP_K overflow fallback, capacity growth, and winner-cache
# invalidation only ever face toy documents.  The growth profile biases
# insert:delete > 1, types longer runs, occasionally pastes 100+ chars, and
# bounds deletes to editor-sized chunks, so soaked documents reach and hold
# realistic lengths while the same convergence/patch asserts run.


def _random_growth_insert(
    rng: random.Random, doc: Doc, max_chars: int
) -> Optional[Dict[str, Any]]:
    length = _text_len(doc)
    index = rng.randrange(length) if length else 0
    if rng.random() < 0.05:  # paste
        num = 100 + rng.randrange(300)
    else:
        num = 1 + rng.randrange(max_chars)
    values = [rng.choice("0123456789abcdef") for _ in range(num)]
    return {"path": ["text"], "action": "insert", "index": index, "values": values}


def _random_bounded_delete(rng: random.Random, doc: Doc) -> Optional[Dict[str, Any]]:
    length = _text_len(doc)
    if length < 2:
        return None
    index = rng.randrange(length - 1) + 1
    count = min(1 + rng.randrange(20), length - index)
    if count <= 0:
        return None
    return {"path": ["text"], "action": "delete", "index": index, "count": count}


# -- nested-object fuzzing (the host structural plane) -----------------------

_NESTED_KEYS = ["k0", "k1", "k2", "list0", "list1"]


def _discover_objects(root: Dict[str, Any]) -> Dict[str, List[List[str]]]:
    """Walk a materialized root view for nested maps and lists (public
    surface only, so the same discovery drives oracle Docs and TpuDocs).
    The root text list is excluded — the classic generators own it.  Takes
    the root snapshot rather than the doc: materializing ``doc.root`` on a
    TpuDoc costs a device text readback, so callers snapshot once."""
    maps: List[List[str]] = [[]]
    lists: List[List[str]] = []

    def walk(value: Dict[str, Any], path: List[str]) -> None:
        for key, child in value.items():
            if isinstance(child, dict):
                maps.append(path + [key])
                if len(path) < 2:
                    walk(child, path + [key])
            elif isinstance(child, list) and (path or key != "text"):
                lists.append(path + [key])

    walk(root, [])
    return {"maps": maps, "lists": lists}


def _random_structural(rng: random.Random, doc: Any) -> Optional[Dict[str, Any]]:
    """One random op against the host structural plane: create/set/del on a
    map, or insert/delete/mark on a nested list."""
    root = doc.root
    objs = _discover_objects(root)
    kind = rng.choice(["makeMap", "makeList", "set", "del", "list_edit", "list_mark"])
    if kind in ("makeMap", "makeList", "set", "del"):
        path = rng.choice(objs["maps"])
        keys = _NESTED_KEYS
        if kind in ("set", "del"):
            # Include 'text' so set/del races the device binding on the root
            # map — exactly where a stale root-view gate would hide (the
            # generators above stay robust via _text_len).
            keys = _NESTED_KEYS + ["text"]
        key = rng.choice(keys)
        if kind == "set":
            return {"path": path, "action": "set", "key": key, "value": rng.randrange(100)}
        if kind == "del":
            return {"path": path, "action": "del", "key": key}
        return {"path": path, "action": kind, "key": key}
    if not objs["lists"]:
        return None
    path = rng.choice(objs["lists"])
    # Resolve the list through the same root snapshot to bound indices.
    node: Any = root
    for p in path:
        node = node[p]
    length = len(node)
    if kind == "list_edit":
        if length and rng.random() < 0.4:
            index = rng.randrange(length)
            return {"path": path, "action": "delete", "index": index, "count": 1}
        index = rng.randrange(length + 1) if length else 0
        values = [rng.choice("uvwxyz") for _ in range(rng.randrange(2) + 1)]
        return {"path": path, "action": "insert", "index": index, "values": values}
    if length == 0:
        return None
    start = rng.randrange(length)
    end = start + rng.randrange(length - start) + 1
    mark_type = rng.choice(["strong", "em"])
    return {
        "path": path,
        "action": rng.choice(["addMark", "removeMark"]),
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }


def fuzz(
    iterations: int = 200,
    seed: int = 0,
    num_docs: int = 3,
    initial_text: str = "ABCDE",
    max_insert_chars: int = 2,
    allow_comment_remove: bool = False,
    doc_factory: Callable[[str], Any] = Doc,
    check_patches: bool = True,
    nested: bool = False,
    report_every: int = 0,
    growth: bool = False,
    growth_target: int = 2000,
    clear_caches_every: int = 0,
    chaos: Optional[str] = None,
    chaos_quiesce: int = 8,
    serve: bool = False,
    serve_shards: int = 1,
    migrate_every: int = 0,
    evict_every: int = 0,
) -> Dict[str, Any]:
    """Run the fuzz loop; raises :class:`FuzzError` with a replayable state.

    ``iterations=0`` runs unbounded (the reference's ``while(true)``,
    fuzz.ts:167) — stop it externally; progress lines (``report_every``) are
    the soak record.

    With ``growth``, the op mix switches to the growth-biased profile
    (3:1 insert:delete, longer runs, occasional 100-400-char pastes,
    bounded deletes) so documents reach and sustain realistic lengths —
    the regime that actually exercises capacity growth, the chunk valves,
    and group-cap fallbacks under adversarial schedules.

    ``clear_caches_every`` drops JAX's compilation caches every N
    iterations (0 = never).  Long growth soaks mint a fresh program per
    distinct (capacity, batch-shape) pair; unbounded, the accumulated
    compiled programs exhaust process memory/mappings after a few hundred
    iterations ("LLVM compilation error: Cannot allocate memory") — the
    periodic clear trades recompiles for a bounded footprint.

    With ``chaos`` (a fault spec, e.g. :data:`DEFAULT_CHAOS_SPEC`), every
    pairwise sync's deliveries run through the spec's ``pubsub_deliver``
    schedule — drops, duplicates and reorders, seeded from ``seed`` — and
    causally-unready survivors are left pending instead of asserted.  Every
    ``chaos_quiesce`` iterations a fault-free full anti-entropy pass from
    the durable log quiesces the fleet, and the standard convergence and
    patch/batch asserts must then hold for *every* replica.  An installed
    process-wide fault plan (``faults.install`` / ``PERITEXT_FAULTS``) can
    additionally inject device-launch faults, driving the engine's
    retry/degradation machinery under the same differential asserts.

    With ``serve``, the same change traffic also drives a **serving plane**
    (runtime/serve.py) fronting a TpuUniverse with one session per fuzz
    replica — session weights, priorities, the batch target and the
    deadline are drawn from the run's rng, so every seed exercises a
    different admission schedule.  Each session submits exactly what its
    doc received (local generations + deliveries, post-chaos-filter), the
    plane steps once per iteration (manual mode — deterministic), and at
    every check point the serve replicas must match the docs span-for-span
    while each session's accumulated patch stream must reconstruct its
    replica (``accumulate_patches``) — the serving-plane byte-identity
    claim under the same adversarial schedules as the engines.

    With ``migrate_every`` (sharded serve mode only), every N iterations a
    random session live-migrates to a random other shard through the full
    elastic protocol (runtime/elastic.py) — under chaos an installed fault
    plan's ``shard_migrate`` site can fail any protocol step, and the
    rollback must keep every quiesce's convergence and byte-identity
    asserts green.

    With ``evict_every`` (sharded serve mode only), every N iterations a
    random session either **evicts** — durable checkpoint, device row
    freed (runtime/lifecycle.py) — or, if already cold, **hydrates**
    back through the full crash-safe protocol.  Cold sessions whose doc
    generates traffic hydrate transparently on submit (the cold-start
    path the lifecycle exists to serve).  Under chaos an installed fault
    plan's ``doc_evict``/``doc_hydrate`` sites can fail any protocol
    step; rollback must leave a failed evict resident and a failed
    hydrate cold — the quiesce's warm-all pass retries from the durable
    checkpoint — with every convergence and byte-identity assert green.
    Combined with ``migrate_every``, migration racing eviction must
    serialize: the elastic plane refuses cold sessions and the lifecycle
    refuses parked (migrating) ones, both tolerated as skips.

    With ``nested``, a share of iterations drive the host structural plane
    (nested makeMap/makeList/set/del, second-list edits and marks) and every
    sync additionally asserts root-view and nested-list-span convergence.
    Patch/batch checking is forced off in that mode: the reference hardcodes
    ``path: ["text"]`` on every list patch (micromerge.ts:643), so a stream
    mixing lists is inherently ambiguous to accumulate — a reference quirk,
    not an engine gap (deterministic patch-interleaving coverage lives in
    tests/test_nested_objects.py).
    """
    rng = random.Random(seed)
    if nested:
        check_patches = False
    if chaos and chaos_quiesce < 1:
        raise ValueError(f"chaos_quiesce must be >= 1, got {chaos_quiesce}")
    if migrate_every and not (serve and serve_shards > 1):
        raise ValueError("migrate_every requires serve mode with shards > 1")
    if evict_every and not (serve and serve_shards > 1):
        raise ValueError("evict_every requires serve mode with shards > 1")
    chaos_plan = FaultPlan.from_spec(chaos, seed=seed) if chaos else None
    docs, all_patches, initial_change = generate_docs(initial_text, num_docs)
    if doc_factory is not Doc:
        # Rebuild replicas with the engine under test from the genesis change.
        docs = [doc_factory(d.actor_id) for d in docs]
        all_patches = [list(apply_changes(d, [initial_change])) for d in docs]
    log = ChangeLog()
    log.record(initial_change)
    comment_history: List[str] = []
    syncs: List[Dict[str, Any]] = []

    serve_plane = None
    serve_sessions: Dict[str, Any] = {}
    lifecycle = None
    lifecycle_errors: tuple = ()
    if serve and serve_shards > 1:
        # Sharded mode (runtime/serve_shard.py): the fuzz replicas are
        # replicas of the SAME document, spread round-robin across
        # ``serve_shards`` universe shards as one ``doc`` replication
        # group — the plane's own cross-shard pubsub fan-out and
        # anti-entropy run under the same chaotic schedules as the
        # engines, and every quiesce asserts byte-identical convergence
        # across shards.
        from peritext_tpu.runtime.serve_shard import ShardedServePlane

        serve_plane = ShardedServePlane(
            serve_shards,
            start=False,  # manual stepping keeps the fuzz deterministic
            batch_target=2 ** rng.randrange(2, 6),
            deadline_ms=float(rng.choice([1, 5, 25])),
            quantum=rng.choice([2, 4, 8]),
        )
        for d in docs:
            serve_sessions[d.actor_id] = serve_plane.session(
                f"s-{d.actor_id}",
                replica=d.actor_id,
                doc="fuzz-doc",
                weight=rng.choice([1, 2, 4]),
                priority=rng.choice(["interactive", "bulk"]),
                record_stream=check_patches,
            )
        for d in docs:
            serve_sessions[d.actor_id].submit([initial_change])
        if serve_plane.drain() != 0:
            raise RuntimeError("sharded plane failed to drain the genesis change")
        if evict_every:
            from peritext_tpu.runtime.lifecycle import (
                DocLifecycle,
                EvictionError,
                HydrationError,
            )

            # Manual ticking (start=False) keeps the fuzz deterministic;
            # the evict_every block below IS the policy loop.
            lifecycle = DocLifecycle(serve_plane, start=False, keep=2)
            lifecycle_errors = (EvictionError, HydrationError)
    elif serve:
        from peritext_tpu.ops import TpuUniverse
        from peritext_tpu.runtime.serve import ServePlane

        serve_uni = TpuUniverse([d.actor_id for d in docs])
        serve_plane = ServePlane(
            serve_uni,
            start=False,  # manual stepping keeps the fuzz deterministic
            batch_target=2 ** rng.randrange(2, 6),
            deadline_ms=float(rng.choice([1, 5, 25])),
            quantum=rng.choice([2, 4, 8]),
        )
        for d in docs:
            serve_sessions[d.actor_id] = serve_plane.session(
                f"s-{d.actor_id}",
                replica=d.actor_id,
                weight=rng.choice([1, 2, 4]),
                priority=rng.choice(["interactive", "bulk"]),
                record_stream=True,
            )
        for d in docs:
            serve_sessions[d.actor_id].submit([initial_change])
        if serve_plane.drain() != 0:
            raise RuntimeError("serving plane failed to drain the genesis change")

    def serve_submit(actor_id: str, changes) -> None:
        if serve_plane is not None and changes:
            try:
                serve_sessions[actor_id].submit(list(changes))
            except lifecycle_errors:
                # An injected doc_hydrate fault failed the transparent
                # cold-start mid-submit; the session stays cold and the
                # durable log redelivers at the next quiesce's warm-all
                # pass (rollback left nothing half-applied).
                evict_stats["cold_submit_failures"] += 1

    def serve_warm_all() -> None:
        """Hydrate every cold session before a quiesce: plane.clock()/
        spans() read the device row, and the catch-up redelivery bypasses
        the cold trap via ``._inner``.  Hydration under an installed
        fault plan can fail (``doc_hydrate`` site) — retry from the
        durable checkpoint; a session that stays cold past the budget is
        a real availability bug."""
        if lifecycle is None:
            return
        for d in docs:
            sess = serve_sessions[d.actor_id]
            for _ in range(8):
                if not sess._cold:
                    break
                try:
                    lifecycle.hydrate(f"s-{d.actor_id}")
                except lifecycle_errors:
                    continue
            else:
                fail(
                    f"session s-{d.actor_id} still cold after 8 hydration "
                    "attempts",
                    {"evict_stats": dict(evict_stats)},
                )

    def serve_check(docs_synced: bool = True) -> None:
        """Catch each serve replica up to ITS doc's clock (dedup-idempotent
        redelivery from the durable log — under chaos the session's lane
        may be missing dropped deliveries the doc will only see at
        quiesce), drain, and assert byte-identity: serve spans == doc
        spans per replica, and each session's accumulated patch stream
        reconstructs its replica.

        Sharded mode instead catches every session up to the LOG frontier
        (the plane's own cross-shard fan-out already out-runs individual
        docs), runs the plane's anti-entropy, and asserts byte-identical
        convergence ACROSS shards; the serve-vs-doc comparison only
        applies when the docs themselves are at the frontier
        (``docs_synced`` — the chaos quiesce)."""
        if serve_plane is None:
            return
        if serve_shards > 1:
            serve_warm_all()
            frontier = log.clock()
            for d in docs:
                missing = log.missing_changes(
                    frontier, serve_plane.clock(d.actor_id)
                )
                if missing:
                    # Catch-up redelivery, not client traffic: bypass the
                    # doc-group fan-out (every sibling is caught up from
                    # the same durable log on its own line — publishing
                    # the suffix N-1 more times would be O(N^2) pure
                    # duplicates through the chaos site).
                    serve_sessions[d.actor_id]._inner.submit(missing)
            serve_plane.anti_entropy()
        else:
            for d in docs:
                serve_submit(
                    d.actor_id,
                    log.missing_changes(dict(d.clock), serve_uni.clock(d.actor_id)),
                )
        leftover = serve_plane.drain()
        if leftover:
            fail(
                f"serving plane left {leftover} submission(s) undeliverable",
                {"serve_stats": dict(serve_plane.stats)},
            )
        if serve_shards > 1:
            first_spans = None
            for d in docs:
                s_spans = serve_plane.spans(d.actor_id)
                if first_spans is None:
                    first_spans = s_spans
                elif s_spans != first_spans:
                    fail(
                        f"cross-shard span divergence on {d.actor_id} "
                        f"(shard {serve_plane.shard_of(d.actor_id)})",
                        {"left": first_spans, "right": s_spans},
                    )
                if docs_synced:
                    doc_spans = d.get_text_with_formatting(["text"])
                    if s_spans != doc_spans:
                        fail(
                            f"serve/doc span divergence on {d.actor_id}",
                            {"serveDoc": s_spans, "batchDoc": doc_spans},
                        )
                if check_patches:
                    accumulated = accumulate_patches(
                        serve_sessions[d.actor_id].patch_log
                    )
                    if accumulated != s_spans:
                        fail(
                            f"serve patch/batch de-sync on {d.actor_id}",
                            {"patchDoc": accumulated, "batchDoc": s_spans},
                        )
            return
        serve_spans = serve_uni.spans_batch()
        for i, d in enumerate(docs):
            doc_spans = d.get_text_with_formatting(["text"])
            if serve_spans[i] != doc_spans:
                fail(
                    f"serve/doc span divergence on {d.actor_id}",
                    {"serveDoc": serve_spans[i], "batchDoc": doc_spans},
                )
            if check_patches:
                accumulated = accumulate_patches(
                    serve_sessions[d.actor_id].patch_log
                )
                if accumulated != serve_spans[i]:
                    fail(
                        f"serve patch/batch de-sync on {d.actor_id}",
                        {"patchDoc": accumulated, "batchDoc": serve_spans[i]},
                    )

    def fail(message: str, extra: Dict[str, Any]) -> None:
        state = {
            "queues": {a: log.changes_for(a) for a in log.actors},
            "syncs": syncs,
            **extra,
        }
        raise FuzzError(message, state)

    def check_pair(a: int, b: int) -> None:
        a_spans = docs[a].get_text_with_formatting(["text"])
        b_spans = docs[b].get_text_with_formatting(["text"])
        if check_patches:
            for side, spans in ((a, a_spans), (b, b_spans)):
                accumulated = accumulate_patches(all_patches[side])
                if accumulated != spans:
                    fail(
                        f"patch/batch de-sync on {docs[side].actor_id}",
                        {"patchDoc": accumulated, "batchDoc": spans},
                    )
        if docs[a].clock != docs[b].clock:
            fail("clock divergence", {"left": dict(docs[a].clock), "right": dict(docs[b].clock)})
        if a_spans != b_spans:
            fail("span divergence", {"left": a_spans, "right": b_spans})
        if nested:
            a_root = docs[a].root
            b_root = docs[b].root
            if a_root != b_root:
                fail(
                    "root-view divergence",
                    {"left": repr(a_root), "right": repr(b_root)},
                )
            # Marked nested lists: spans must agree too (marks are
            # invisible in the plain root view).  Reuses the snapshot
            # just compared.
            for path in _discover_objects(a_root)["lists"]:
                ls = docs[a].get_text_with_formatting(path)
                rs = docs[b].get_text_with_formatting(path)
                if ls != rs:
                    fail(
                        f"nested span divergence at {path}",
                        {"left": ls, "right": rs},
                    )

    def quiesce_and_check() -> None:
        """Fault-free full anti-entropy from the durable log, then the
        standard convergence/patch asserts for EVERY replica."""
        frontier = log.clock()
        for i, d in enumerate(docs):
            all_patches[i].extend(
                apply_changes(d, log.missing_changes(frontier, d.clock))
            )
        for i in range(1, len(docs)):
            check_pair(0, i)
        serve_check()

    done = 0
    max_doc_len = 0
    migrate_stats = {
        "attempts": 0, "migrations": 0, "rollbacks": 0, "skipped_cold": 0,
    }
    evict_stats = {
        "attempts": 0, "evictions": 0, "hydrations": 0, "rollbacks": 0,
        "skipped": 0, "cold_submit_failures": 0,
    }
    # True while chaotic syncs have happened since the last fault-free
    # quiesce (drives both the heartbeat wording and the mandatory final
    # quiesce — `done % chaos_quiesce` alone misses a no-op last iteration).
    chaos_unverified = False
    for done in itertools.count(1) if iterations == 0 else range(1, iterations + 1):
        # Clear BEFORE op generation: a no-op iteration's `continue` must
        # not skip a scheduled clear (the interval this knob bounds is the
        # accumulation margin before allocation failure).
        if clear_caches_every and done % clear_caches_every == 0:
            import jax

            jax.clear_caches()
        target = rng.randrange(len(docs))
        doc = docs[target]
        if growth:
            # 3:1 insert bias until the doc reaches the sustain target,
            # then 1:2 so the soak HOLDS a realistic length indefinitely
            # (unbounded growth would slow the O(n) oracle + patch checks
            # to a crawl and stop exercising delete/valve paths).
            if _text_len(doc) < growth_target:
                kinds = ["insert", "insert", "insert", "remove", "addMark", "removeMark"]
            else:
                kinds = ["insert", "remove", "remove", "addMark", "removeMark"]
        else:
            kinds = ["insert", "remove", "addMark", "removeMark"]
        if nested:
            kinds += ["structural", "structural"]
        op_kind = rng.choice(kinds)
        if op_kind == "insert":
            op = (
                _random_growth_insert(rng, doc, max(max_insert_chars, 8) * 2)
                if growth
                else _random_insert(rng, doc, max_insert_chars)
            )
        elif op_kind == "remove":
            op = (
                _random_bounded_delete(rng, doc)
                if growth
                else _random_delete(rng, doc)
            )
        elif op_kind == "addMark":
            op = _random_add_mark(rng, doc, comment_history)
        elif op_kind == "structural":
            op = _random_structural(rng, doc)
        else:
            op = _random_remove_mark(rng, doc, comment_history, allow_comment_remove)
        if op is None:
            continue
        change, patches = doc.change([op])
        log.record(change)
        all_patches[target].extend(patches)
        serve_submit(doc.actor_id, [change])
        max_doc_len = max(max_doc_len, _text_len(doc))

        left = rng.randrange(len(docs))
        right = rng.randrange(len(docs))
        while right == left:
            right = rng.randrange(len(docs))
        syncs.append({"left": docs[left].actor_id, "right": docs[right].actor_id})

        if chaos_plan is not None:
            # Chaotic delivery: each direction's missing-changes stream runs
            # through the pubsub_deliver schedule (per-receiver holdback
            # buffers), and causal gaps are tolerated — the durable log
            # redelivers on a later sync.
            to_right = chaos_plan.filter_stream(
                "pubsub_deliver",
                log.missing_changes(docs[left].clock, docs[right].clock),
                stream=docs[right].actor_id,
            )
            to_left = chaos_plan.filter_stream(
                "pubsub_deliver",
                log.missing_changes(docs[right].clock, docs[left].clock),
                stream=docs[left].actor_id,
            )
            all_patches[right].extend(apply_changes(docs[right], to_right, allow_gaps=True))
            all_patches[left].extend(apply_changes(docs[left], to_left, allow_gaps=True))
            # The serving plane sees exactly what the docs saw (the
            # post-filter streams); causally-unready submissions defer in
            # the session lanes until redelivery makes them ready.
            serve_submit(docs[right].actor_id, to_right)
            serve_submit(docs[left].actor_id, to_left)
            if serve_plane is not None:
                serve_plane.step()
            # Convergence is only claimable at quiesce points; other
            # iterations stay chaotic and unverified.
            chaos_unverified = True
            verified = done % chaos_quiesce == 0
            if verified:
                quiesce_and_check()
                chaos_unverified = False
        else:
            to_right = log.missing_changes(docs[left].clock, docs[right].clock)
            to_left = log.missing_changes(docs[right].clock, docs[left].clock)
            all_patches[right].extend(apply_changes(docs[right], to_right))
            all_patches[left].extend(apply_changes(docs[left], to_left))
            serve_submit(docs[right].actor_id, to_right)
            serve_submit(docs[left].actor_id, to_left)
            if serve_plane is not None:
                serve_plane.step()
                if done % chaos_quiesce == 0:
                    serve_check(docs_synced=False)
            check_pair(left, right)
            verified = True
        if migrate_every and done % migrate_every == 0:
            # Live migration under fire (ISSUE 17): every N iterations a
            # random session moves to a random OTHER shard mid-stream via
            # the full elastic protocol (drain -> export -> provision ->
            # import -> commit).  Under chaos an installed fault plan's
            # ``shard_migrate`` site can fail any step — the rollback must
            # leave the source shard authoritative, and the next quiesce's
            # cross-shard convergence + byte-identity asserts hold either
            # way.
            from peritext_tpu.runtime import elastic as _elastic

            victim = docs[rng.randrange(len(docs))]
            sess = serve_sessions[victim.actor_id]
            target_shard = (sess.shard + rng.randrange(1, serve_shards)) % serve_shards
            migrate_stats["attempts"] += 1
            try:
                _elastic.migrate_session(serve_plane, f"s-{victim.actor_id}", target_shard)
                migrate_stats["migrations"] += 1
            except _elastic.MigrationError:
                migrate_stats["rollbacks"] += 1
            except ValueError:
                if not evict_every:
                    raise
                # Migration racing eviction: the elastic plane refuses an
                # evicted (cold) session outright — the defined
                # serialization with the lifecycle, not a failure.
                migrate_stats["skipped_cold"] += 1
        if evict_every and done % evict_every == 0:
            # Multi-tenant lifecycle under fire (ISSUE 20): every N
            # iterations a random session either evicts (durable
            # checkpoint + device row freed) or, if already cold,
            # hydrates back through the full crash-safe protocol
            # (runtime/lifecycle.py).  Under chaos an installed fault
            # plan's doc_evict/doc_hydrate sites can fail any step — a
            # failed evict rolls back resident, a failed hydrate stays
            # cold for the quiesce's warm-all retry, and the convergence
            # + byte-identity asserts must hold either way.
            victim = docs[rng.randrange(len(docs))]
            vsess = serve_sessions[victim.actor_id]
            evict_stats["attempts"] += 1
            try:
                if vsess._cold:
                    lifecycle.hydrate(f"s-{victim.actor_id}")
                    evict_stats["hydrations"] += 1
                else:
                    lifecycle.evict(f"s-{victim.actor_id}")
                    evict_stats["evictions"] += 1
            except lifecycle_errors:
                evict_stats["rollbacks"] += 1
            except ValueError:
                # Racing a live migration (parked session): the
                # lifecycle serializes by refusing, not deadlocking.
                evict_stats["skipped"] += 1
        # Progress AFTER the iteration's checks: a soak line only claims
        # "ok" for iterations that actually converged — chaotic
        # non-quiesce iterations still emit a heartbeat (a wedged soak must
        # stay distinguishable from a slow one) but say so.
        if report_every and done % report_every == 0:
            length = sum(
                len(s["text"]) for s in docs[0].get_text_with_formatting(["text"])
            )
            if verified:
                print(f"fuzz: {done} iterations ok, doc length {length}", flush=True)
            else:
                print(
                    f"fuzz: {done} iterations (chaotic; convergence pending "
                    f"next quiesce), doc length {length}",
                    flush=True,
                )

    if chaos_plan is not None and chaos_unverified:
        # Final quiesce: the run must never end on unchecked chaotic
        # iterations (or with deliveries still in the holdback buffers) —
        # a success return means every replica converged at the end.
        quiesce_and_check()
    elif chaos_plan is None:
        # The serving plane must end drained and byte-identical too.
        serve_check(docs_synced=False)

    # Windowed-merge engagement across every device-backed replica (the
    # frontier-bounded path, ISSUE 12): aggregated TpuUniverse stats, so a
    # growth run's footer can report how often edits stayed O(window).
    window_stats = {"launches": 0, "windowed_launches": 0, "window_fallbacks": 0}
    for d in docs:
        uni = getattr(d, "_uni", None)
        if uni is not None:
            for k in window_stats:
                window_stats[k] += int(uni.stats.get(k, 0))

    return {
        "docs": docs,
        "log": log,
        "patches": all_patches,
        "iterations": done,
        "max_doc_len": max_doc_len,
        "window_stats": window_stats,
        "final_spans": docs[0].get_text_with_formatting(["text"]),
        "serve_stats": dict(serve_plane.stats) if serve_plane is not None else None,
        "migrate_stats": migrate_stats if migrate_every else None,
        "evict_stats": dict(evict_stats, lifecycle=dict(lifecycle.stats))
        if evict_every
        else None,
    }


def _main() -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="Convergence fuzzer (reference: test/fuzz.ts). "
        "iters=0 runs unbounded, like the reference's while(true)."
    )
    parser.add_argument("iters", nargs="?", type=int, default=1000)
    parser.add_argument("seed", nargs="?", type=int, default=0)
    parser.add_argument(
        "--engine", choices=["oracle", "tpu", "mixed"], default="oracle",
        help="doc factory under test (tpu = all TpuDoc; mixed = alternating "
        "oracle/TpuDoc replicas — the strongest cross-engine differential)",
    )
    parser.add_argument("--nested", action="store_true", help="also fuzz nested objects")
    parser.add_argument(
        "--serve", action="store_true",
        help="also drive the serving plane (runtime/serve.py): one session "
        "per replica with rng-drawn weights/priorities/deadlines, stepped "
        "per iteration, byte-identity asserted at every check point",
    )
    parser.add_argument(
        "--shards", type=int, default=1,
        help="with --serve: partition the sessions across this many "
        "universe shards (runtime/serve_shard.py) as one cross-shard "
        "document group — the plane's pubsub fan-out + anti-entropy run "
        "under the same chaotic delivery, and every quiesce asserts "
        "byte-identical convergence across shards",
    )
    parser.add_argument(
        "--migrate-every", type=int, default=0, metavar="N",
        help="with --serve --shards K: live-migrate a random session to a "
        "random other shard every N iterations via the full elastic "
        "protocol (runtime/elastic.py); under --chaos a fault plan's "
        "shard_migrate site can fail any step and the rollback must keep "
        "every quiesce's convergence + byte-identity asserts green "
        "(0 = never)",
    )
    parser.add_argument(
        "--evict-every", type=int, default=0, metavar="N",
        help="with --serve --shards K: every N iterations a random session "
        "evicts (durable checkpoint, device row freed) or hydrates back "
        "through the full lifecycle protocol (runtime/lifecycle.py); cold "
        "sessions also hydrate transparently on submit; under --chaos a "
        "fault plan's doc_evict/doc_hydrate sites can fail any step and "
        "rollback must keep every quiesce's convergence + byte-identity "
        "asserts green (0 = never)",
    )
    parser.add_argument(
        "--chaos", nargs="?", const=DEFAULT_CHAOS_SPEC, default=None, metavar="SPEC",
        help="chaotic delivery between replicas (fault spec; bare flag uses "
        f"{DEFAULT_CHAOS_SPEC!r}); convergence asserted at fault-free "
        "quiesce points",
    )
    parser.add_argument(
        "--chaos-quiesce", type=int, default=8,
        help="iterations between fault-free quiesce/assert passes under --chaos",
    )
    parser.add_argument(
        "--growth", action="store_true",
        help="growth-biased op profile: docs reach/sustain 1k+ chars "
        "(exercises capacity growth, chunk valves, group-cap fallbacks)",
    )
    parser.add_argument(
        "--growth-target", type=int, default=2000,
        help="doc length the growth profile sustains (insert-biased below, "
        "delete-biased above)",
    )
    parser.add_argument(
        "--clear-caches-every", type=int, default=0,
        help="drop JAX compilation caches every N iterations (bounds a "
        "long soak's per-shape program accumulation; 0 = never)",
    )
    parser.add_argument(
        "--report-every", type=int, default=1000,
        help="progress line every N iterations (0 = silent)",
    )
    parser.add_argument(
        "--trace-dir", default="traces", help="where failure traces are written"
    )
    parser.add_argument(
        "--platform", default="cpu",
        help="JAX platform for --engine tpu (default cpu; 'ambient' keeps "
        "the process default, i.e. the relayed TPU when it serves)",
    )
    args = parser.parse_args()

    if args.engine in ("tpu", "mixed"):
        if args.platform != "ambient":
            import jax

            jax.config.update("jax_platforms", args.platform)
        from peritext_tpu.ops.doc import TpuDoc

        if args.engine == "mixed":
            flip = itertools.cycle([TpuDoc, Doc])

            def factory(actor_id: str) -> Any:
                return next(flip)(actor_id)

        else:
            factory: Callable[[str], Any] = TpuDoc
    else:
        factory = Doc
    if args.chaos or args.serve:
        # Chaos/serve runs are self-describing: the registry collects the
        # mirrored fault tallies (faults.<site>.<key>) plus the resilience
        # counters, and the run prints one summary line at the end —
        # PERITEXT_TRACE/PERITEXT_METRICS additionally activate the tracer
        # and the exit dump as usual.
        from peritext_tpu.runtime import telemetry

        telemetry.enable()
    try:
        result = fuzz(
            iterations=args.iters,
            seed=args.seed,
            doc_factory=factory,
            nested=args.nested,
            report_every=args.report_every,
            growth=args.growth,
            growth_target=args.growth_target,
            clear_caches_every=args.clear_caches_every,
            chaos=args.chaos,
            chaos_quiesce=args.chaos_quiesce,
            serve=args.serve or args.shards > 1,
            serve_shards=args.shards,
            migrate_every=args.migrate_every,
            evict_every=args.evict_every,
        )
    except FuzzError as err:
        path = os.path.join(args.trace_dir, f"fail-seed{args.seed}.json")
        err.save(path)
        if args.chaos or args.serve:
            _print_telemetry_summary()
        print(f"FAILED: {err}; trace written to {path}")
        raise
    if args.chaos or args.serve:
        _print_telemetry_summary()
    print(
        f"ok: {result['iterations']} iterations, final doc length "
        f"{sum(len(s['text']) for s in result['final_spans'])}"
    )
    if result.get("migrate_stats"):
        ms = result["migrate_stats"]
        print(
            f"migrate: {ms['migrations']}/{ms['attempts']} sessions moved "
            f"live ({ms['rollbacks']} rolled back, {ms['skipped_cold']} "
            f"skipped cold)",
            flush=True,
        )
    if result.get("evict_stats"):
        es = result["evict_stats"]
        lc = es["lifecycle"]
        print(
            f"lifecycle: {es['evictions']} evicted / {es['hydrations']} "
            f"explicitly hydrated over {es['attempts']} attempts "
            f"({es['rollbacks']} rolled back, {es['skipped']} skipped racing "
            f"migration, {es['cold_submit_failures']} cold submits failed "
            f"over to quiesce); protocol totals: "
            f"{lc['evictions']} evictions, {lc['hydrations']} hydrations, "
            f"{lc['corrupt_fallbacks']} corrupt fallbacks, "
            f"{lc['full_replays']} full replays, "
            f"{lc['replayed_changes']} changes replayed",
            flush=True,
        )
    if args.growth:
        ws = result["window_stats"]
        engaged = (
            100.0 * ws["windowed_launches"] / ws["launches"]
            if ws["launches"]
            else 0.0
        )
        print(
            f"growth: sustained {sum(len(s['text']) for s in result['final_spans'])} "
            f"chars (max {result['max_doc_len']}), windowed merge "
            f"{ws['windowed_launches']}/{ws['launches']} launches "
            f"({engaged:.0f}%), census fallbacks {ws['window_fallbacks']}",
            flush=True,
        )


def _print_telemetry_summary() -> None:
    import json

    from peritext_tpu.runtime import health, slo, telemetry

    summary = telemetry.summary()
    summary.pop("slo", None)  # the dedicated slo: line below supersedes it
    # Causal health rides along with the tallies: the e2e latency
    # percentiles appear whenever the engine under test fed them (TpuDoc /
    # queue / pubsub seams), and the flight-recorder counts are always
    # stated — a soak that silently overwrote its ring is a soak whose
    # post-mortem window shrank, which the operator should see.
    rec_n, rec_dropped = telemetry.recorder_stats()
    summary.setdefault("recorder_events", rec_n)
    summary.setdefault("recorder_dropped", rec_dropped)
    # The serving-plane tallies get their own diffable line (the admission/
    # batching/shed behavior of a --serve run, incl. the admit-to-applied
    # percentiles riding in the e2e block above).
    serve_summary = summary.pop("serve", None)
    print("telemetry: " + json.dumps(summary, sort_keys=True), flush=True)
    if serve_summary:
        print("serve: " + json.dumps(serve_summary, sort_keys=True), flush=True)
    health_summary = health.summary()
    if health_summary:
        print("health: " + json.dumps(health_summary, sort_keys=True), flush=True)
    # SLO verdicts get their own diffable footer line whenever a
    # PERITEXT_SLO plan was active for the run.
    slo_summary = slo.summary()
    if slo_summary:
        print("slo: " + json.dumps(slo_summary, sort_keys=True), flush=True)


if __name__ == "__main__":
    _main()
