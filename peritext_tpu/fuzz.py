"""Generative convergence fuzzer.

Reference: test/fuzz.ts — N replicas, random ops, random pairwise
anti-entropy syncs, asserting after every sync that (a) the accumulated patch
stream equals the batch flatten on both replicas and (b) the pair converged
(equal clocks, equal spans).  Failures serialize a full reproducible state
(queues + syncs), which :func:`peritext_tpu.replay.replay_change_log` can
re-execute.

Differences from the reference fuzzer, on purpose:
- Seeded/deterministic (reference uses Math.random with no seed).
- Comment removeMark is generated *as a removeMark* with a known id.  (The
  reference's removeMarkChange constructs an addMark by mistake, fuzz.ts:78 —
  so comment removal was never actually fuzzed upstream.)  Comment-remove
  convergence holds under this engine's per-id LWW semantics.
- Also drives the engine under test via ``doc_factory`` so the same harness
  differential-tests the TPU engine against the oracle.
"""
from __future__ import annotations

import json
import math
import random
from typing import Any, Callable, Dict, List, Optional

from peritext_tpu.oracle import Doc, accumulate_patches
from peritext_tpu.runtime.log import ChangeLog
from peritext_tpu.runtime.sync import apply_changes
from peritext_tpu.testing import generate_docs

MARK_TYPES = ["strong", "em", "link", "comment"]
EXAMPLE_URLS = [f"{c}.com" for c in "ABCDEFGHIJKLMNOPQRSTUVWXYZ"]


class FuzzError(AssertionError):
    def __init__(self, message: str, state: Dict[str, Any]):
        super().__init__(message)
        self.state = state

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.state, f)


def _random_add_mark(rng: random.Random, doc: Doc, comment_history: List[str]) -> Dict[str, Any]:
    length = len(doc.root["text"])
    start = rng.randrange(length)
    end = start + rng.randrange(length - start) + 1
    mark_type = rng.choice(MARK_TYPES)
    op: Dict[str, Any] = {
        "path": ["text"],
        "action": "addMark",
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "link":
        op["attrs"] = {"url": rng.choice(EXAMPLE_URLS)}
    elif mark_type == "comment":
        comment_id = f"comment-{rng.randrange(1 << 16):04x}"
        comment_history.append(comment_id)
        op["attrs"] = {"id": comment_id}
    return op


def _random_remove_mark(
    rng: random.Random, doc: Doc, comment_history: List[str], allow_comment_remove: bool
) -> Dict[str, Any]:
    length = len(doc.root["text"])
    start = rng.randrange(length)
    end = start + rng.randrange(length - start) + 1
    choices = [t for t in MARK_TYPES if allow_comment_remove or t != "comment"]
    mark_type = rng.choice(choices)
    op: Dict[str, Any] = {
        "path": ["text"],
        "action": "removeMark",
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "comment":
        if not comment_history:
            op["markType"] = "strong"
        else:
            op["attrs"] = {"id": rng.choice(comment_history)}
    return op


def _random_insert(rng: random.Random, doc: Doc, max_chars: int) -> Optional[Dict[str, Any]]:
    length = len(doc.root["text"])
    index = rng.randrange(length) if length else 0
    num = rng.randrange(max_chars)
    values = [rng.choice("0123456789abcdef") for _ in range(num)]
    return {"path": ["text"], "action": "insert", "index": index, "values": values}


def _random_delete(rng: random.Random, doc: Doc) -> Optional[Dict[str, Any]]:
    length = len(doc.root["text"])
    # Faithful to the reference's bounds (fuzz.ts:128-129), which never
    # delete the entire document (a noted real bug when you do).
    index = rng.randrange(length) + 1
    count = math.ceil(rng.random() * (length - index))
    if count <= 0:
        return None
    return {"path": ["text"], "action": "delete", "index": index, "count": count}


def fuzz(
    iterations: int = 200,
    seed: int = 0,
    num_docs: int = 3,
    initial_text: str = "ABCDE",
    max_insert_chars: int = 2,
    allow_comment_remove: bool = False,
    doc_factory: Callable[[str], Any] = Doc,
    check_patches: bool = True,
) -> Dict[str, Any]:
    """Run the fuzz loop; raises :class:`FuzzError` with a replayable state."""
    rng = random.Random(seed)
    docs, all_patches, initial_change = generate_docs(initial_text, num_docs)
    if doc_factory is not Doc:
        # Rebuild replicas with the engine under test from the genesis change.
        docs = [doc_factory(d.actor_id) for d in docs]
        all_patches = [list(apply_changes(d, [initial_change])) for d in docs]
    log = ChangeLog()
    log.record(initial_change)
    comment_history: List[str] = []
    syncs: List[Dict[str, Any]] = []

    def fail(message: str, extra: Dict[str, Any]) -> None:
        state = {
            "queues": {a: log.changes_for(a) for a in log.actors},
            "syncs": syncs,
            **extra,
        }
        raise FuzzError(message, state)

    for _ in range(iterations):
        target = rng.randrange(len(docs))
        doc = docs[target]
        op_kind = rng.choice(["insert", "remove", "addMark", "removeMark"])
        if op_kind == "insert":
            op = _random_insert(rng, doc, max_insert_chars)
        elif op_kind == "remove":
            op = _random_delete(rng, doc)
        elif op_kind == "addMark":
            op = _random_add_mark(rng, doc, comment_history)
        else:
            op = _random_remove_mark(rng, doc, comment_history, allow_comment_remove)
        if op is None:
            continue
        change, patches = doc.change([op])
        log.record(change)
        all_patches[target].extend(patches)

        left = rng.randrange(len(docs))
        right = rng.randrange(len(docs))
        while right == left:
            right = rng.randrange(len(docs))
        syncs.append({"left": docs[left].actor_id, "right": docs[right].actor_id})

        all_patches[right].extend(
            apply_changes(docs[right], log.missing_changes(docs[left].clock, docs[right].clock))
        )
        all_patches[left].extend(
            apply_changes(docs[left], log.missing_changes(docs[right].clock, docs[left].clock))
        )

        left_spans = docs[left].get_text_with_formatting(["text"])
        right_spans = docs[right].get_text_with_formatting(["text"])

        if check_patches:
            for side, spans in ((left, left_spans), (right, right_spans)):
                accumulated = accumulate_patches(all_patches[side])
                if accumulated != spans:
                    fail(
                        f"patch/batch de-sync on {docs[side].actor_id}",
                        {"patchDoc": accumulated, "batchDoc": spans},
                    )
        if docs[left].clock != docs[right].clock:
            fail("clock divergence", {"left": dict(docs[left].clock), "right": dict(docs[right].clock)})
        if left_spans != right_spans:
            fail("span divergence", {"left": left_spans, "right": right_spans})

    return {
        "docs": docs,
        "log": log,
        "patches": all_patches,
        "final_spans": docs[0].get_text_with_formatting(["text"]),
    }


if __name__ == "__main__":
    import sys

    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    try:
        result = fuzz(iterations=iters, seed=seed)
    except FuzzError as err:
        path = f"traces/fail-seed{seed}.json"
        err.save(path)
        print(f"FAILED: {err}; trace written to {path}")
        raise
    print(f"ok: {iters} iterations, final doc length "
          f"{sum(len(s['text']) for s in result['final_spans'])}")
