"""Trace capture and replay — the framework's first-class debug artifact.

Two trace formats:

1. **Change-log traces** (the reference's ``traces/*.json`` failure dumps,
   written by test/fuzz.ts:16-20): ``{"queues": {actor: [Change, ...]}, ...}``.
   :func:`replay_change_log` reconstructs fresh replicas purely from the raw
   changes, which exercises the whole remote-ingestion path.

2. **Event traces** (the reference's playback.ts ``Trace``): a stream of
   input operations tagged with an editor id, interleaved with ``sync``
   events.  :func:`execute_trace` drives a set of replicas through the
   stream; :func:`concurrent_spec_to_trace` expands a concurrent-edit spec
   into keystroke-granular events (playback.ts:13-52 testToTrace /
   simulateTypingForInputOp).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from peritext_tpu.oracle import Doc
from peritext_tpu.runtime.log import ChangeLog
from peritext_tpu.runtime.sync import apply_changes


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def replay_change_log(
    queues: Dict[str, List[Dict[str, Any]]],
    doc_factory=Doc,
) -> Dict[str, Any]:
    """Rebuild one replica per actor from raw change queues.

    Every replica ingests *all* changes (its own included) through the causal
    retry loop, exactly as a replica recovering from a change log would.
    Returns per-actor docs and their materialized spans.
    """
    all_changes: List[Dict[str, Any]] = []
    for changes in queues.values():
        all_changes.extend(changes)

    docs: Dict[str, Any] = {}
    spans: Dict[str, Any] = {}
    for actor in queues:
        doc = doc_factory(actor)
        apply_changes(doc, list(all_changes))
        docs[actor] = doc
        spans[actor] = doc.get_text_with_formatting(["text"])
    return {"docs": docs, "spans": spans}


def assert_replay_converges(queues: Dict[str, List[Dict[str, Any]]], doc_factory=Doc) -> Any:
    """Replay a change log and assert all reconstructed replicas agree."""
    result = replay_change_log(queues, doc_factory)
    spans = list(result["spans"].values())
    clocks = [dict(doc.clock) for doc in result["docs"].values()]
    for other in spans[1:]:
        assert other == spans[0], f"replay diverged: {other} != {spans[0]}"
    for other in clocks[1:]:
        assert other == clocks[0], f"clock diverged: {other} != {clocks[0]}"
    return spans[0]


# ---------------------------------------------------------------------------
# Event traces (reference playback.ts)
# ---------------------------------------------------------------------------


def simulate_typing_for_input_op(editor_id: str, op: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand an insert into per-keystroke events (playback.ts:39-52)."""
    if op["action"] == "insert":
        return [
            {
                **op,
                "editorId": editor_id,
                "path": ["text"],
                "values": [value],
                "index": op["index"] + i,
            }
            for i, value in enumerate(op["values"])
        ]
    return [{**op, "editorId": editor_id, "path": ["text"]}]


def concurrent_spec_to_trace(
    initial_text: str,
    input_ops1: Sequence[Dict[str, Any]],
    input_ops2: Sequence[Dict[str, Any]],
    editors: Sequence[str] = ("alice", "bob"),
) -> List[Dict[str, Any]]:
    """Reference playback.ts:13-37 (testToTrace)."""
    trace: List[Dict[str, Any]] = [
        {"editorId": editors[0], "path": [], "action": "makeList", "key": "text"},
        {"action": "sync"},
        {
            "editorId": editors[0],
            "path": ["text"],
            "action": "insert",
            "index": 0,
            "values": list(initial_text),
        },
        {"action": "sync"},
    ]
    for op in input_ops1:
        trace.extend(simulate_typing_for_input_op(editors[0], op))
    for op in input_ops2:
        trace.extend(simulate_typing_for_input_op(editors[1], op))
    trace.append({"action": "sync"})
    return trace


class TraceSession:
    """Drives named replicas through an event trace with batched syncing.

    The playback engine (playback.ts:82-121) minus the DOM: each editor has a
    doc and an outbound queue; ``sync`` flushes every queue through a shared
    change log and anti-entropy delivery.
    """

    def __init__(self, editor_ids: Sequence[str], doc_factory=Doc) -> None:
        self.docs: Dict[str, Any] = {e: doc_factory(e) for e in editor_ids}
        self.log = ChangeLog()
        self.pending: Dict[str, List[Dict[str, Any]]] = {e: [] for e in editor_ids}
        self.patches: Dict[str, List[Dict[str, Any]]] = {e: [] for e in editor_ids}

    def apply_event(self, event: Dict[str, Any]) -> None:
        action = event["action"]
        if action == "sync":
            self.sync()
            return
        if action == "restart":  # playback.ts:102 — a demo-loop no-op here
            return
        editor_id = event["editorId"]
        doc = self.docs[editor_id]
        op = {k: v for k, v in event.items() if k not in ("editorId", "delay")}
        change, patches = doc.change([op])
        self.patches[editor_id].extend(patches)
        self.pending[editor_id].append(change)

    def sync(self) -> None:
        for editor_id, changes in self.pending.items():
            for change in changes:
                self.log.record(change)
            self.pending[editor_id] = []
        for editor_id, doc in self.docs.items():
            missing = self.log.missing_changes(self.log.clock(), doc.clock)
            self.patches[editor_id].extend(apply_changes(doc, missing))

    def run(self, trace: Sequence[Dict[str, Any]]) -> None:
        for event in trace:
            self.apply_event(event)

    def spans(self, editor_id: Optional[str] = None) -> Any:
        if editor_id is not None:
            return self.docs[editor_id].get_text_with_formatting(["text"])
        return {e: d.get_text_with_formatting(["text"]) for e, d in self.docs.items()}
